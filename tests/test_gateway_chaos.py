"""Serving-gateway chaos tier: REAL OS processes, real TCP, mid-run chaos.

Topology: 3 worker processes form a cluster (membership over real
sockets, the multi_process conductor for barriers). Node 0 supervises a
gateway-server CHILD process (examples/serving_gateway.py serve — a
DeviceShardRegion of counter entities with an armed WAL + checkpoint
dir, behind admission + SLO tracking) and injects the chaos legs over
the wire as the `__admin` tenant; nodes 1-2 are sustained-load clients
reconnecting through the outages.

Chaos legs, in order, all under load:
  1. shard rebalance            (admin op -> region.rebalance)
  2. kill -9 the gateway child  (restart with --restore: snapshot + WAL)
  3. device failover 2 -> 1     (admin op -> region.failover)

Invariant: with sent_sum = sum over every wire send attempt and
acked_sum = sum over "ok" replies,

    acked_sum <= final_total <= sent_sum

i.e. ZERO lost acknowledged writes (the WAL guarantee) and nothing
applied that was never sent (at-most-once per attempt). The run also
emits the p50/p99 SLO artifact."""

import pytest

from akka_tpu.testkit.multi_process import spawn_nodes

pytestmark = pytest.mark.slow

_COMMON = r"""
import json, os, signal, socket, subprocess, sys, tempfile, time
import akka_tpu
from akka_tpu import ActorSystem
from akka_tpu.cluster import Cluster
from akka_tpu.gateway import GatewayClient
from akka_tpu.testkit.dilation import dilated, dilated_s
from akka_tpu.testkit.multi_process import (node_barrier, node_index,
                                            node_count, node_result)

IDX = node_index()
N = node_count()
BASE_PORT = int(os.environ["AKKA_TPU_TEST_BASE_PORT"])
GW_PORT = BASE_PORT + 37
STOP_FILE = os.path.join(tempfile.gettempdir(),
                         f"gw_chaos_stop_{BASE_PORT}")
EXAMPLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(akka_tpu.__file__))), "examples", "serving_gateway.py")

def make_system(extra=None):
    cfg = {"akka": {"actor": {"provider": "cluster"},
                    "stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "remote": {"transport": "tcp",
                               "canonical": {"hostname": "127.0.0.1",
                                             "port": BASE_PORT + IDX}},
                    "cluster": {"gossip-interval": "0.1s",
                                "leader-actions-interval": "0.1s"}}}
    if extra:
        cfg["akka"].update(extra)
    return ActorSystem(f"mp{IDX}", cfg)

def up_count(system):
    return len([m for m in Cluster.get(system).state.members
                if m.status.value == "Up"])

def await_(cond, secs, what):
    deadline = time.monotonic() + dilated(secs)
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError("timeout waiting for " + what)

def spawn_serve(directory, restore=False, durable=False, dedup=False):
    cmd = [sys.executable, EXAMPLE, "serve", "--port", str(GW_PORT),
           "--dir", directory, "--devices", "2", "--shards", "4",
           "--eps", "16", "--rate", "1000", "--burst", "500"]
    if restore:
        cmd.append("--restore")
    if durable:
        cmd.append("--durable")
    if dedup:
        cmd.append("--dedup")
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + dilated(120.0)
    while time.monotonic() < deadline:
        line = p.stdout.readline()
        if not line:
            raise AssertionError(f"serve child died rc={p.poll()}")
        sys.stderr.write(f"[serve:{IDX}] {line}")
        if line.startswith("READY "):
            return p
    raise AssertionError("serve child never printed READY")
"""


def test_gateway_survives_rebalance_crash_and_failover():
    worker = _COMMON + r"""
system = make_system()
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot", timeout=dilated(120.0))
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 3, 60, "3 members Up")
node_barrier("converged", timeout=dilated(120.0))

if IDX == 0:
    # ------------------------------------------ gateway supervisor + chaos
    if os.path.exists(STOP_FILE):
        os.remove(STOP_FILE)
    gw_dir = tempfile.mkdtemp(prefix="gw_chaos_")
    serve = spawn_serve(gw_dir)
    node_barrier("gw_up", timeout=dilated(180.0))
    admin = GatewayClient("127.0.0.1", GW_PORT, timeout=30.0)
    legs = {}

    time.sleep(dilated(2.0))  # load flowing against the initial placement
    rep = admin.request_retry("__admin", "", "rebalance", 0.0,
                              deadline_s=dilated(60.0))
    legs["rebalance"] = rep["status"]

    time.sleep(dilated(2.0))
    serve.send_signal(signal.SIGKILL)   # chaos: the process, not the data
    serve.wait()
    admin.close()
    serve = spawn_serve(gw_dir, restore=True)
    legs["crash_restore"] = "ok"

    time.sleep(dilated(2.0))
    rep = admin.request_retry("__admin", "", "failover", 1.0,
                              deadline_s=dilated(90.0))
    legs["failover"] = rep["status"]

    time.sleep(dilated(3.0))  # post-failover traffic on the survivor mesh
    open(STOP_FILE, "w").close()
    node_barrier("load_done", timeout=dilated(240.0))

    # loads are quiesced: the conserved-value probe is stable now
    final = admin.request_retry("__admin", "", "sum",
                                deadline_s=dilated(60.0))
    artifact = admin.request_retry("__admin", "", "artifact",
                                   deadline_s=dilated(60.0))["data"]
    admin.close()
    serve.send_signal(signal.SIGTERM)
    try:
        serve.wait(timeout=dilated(30.0))
    except subprocess.TimeoutExpired:
        serve.kill()
    os.remove(STOP_FILE)
    node_result({"role": "chaos", "legs": legs,
                 "final_total": float(final["value"]),
                 "artifact": {k: v for k, v in artifact.items()
                              if k != "per_tenant"}})
else:
    # ------------------------------------------------- sustained-load client
    node_barrier("gw_up", timeout=dilated(180.0))
    client = GatewayClient("127.0.0.1", GW_PORT, timeout=10.0)
    sent_sum = acked_sum = 0.0
    counts = {"ok": 0, "shed": 0, "error": 0, "conn_error": 0}
    i = 0
    while not os.path.exists(STOP_FILE):
        i += 1
        value = float(i % 5 + 1)
        # one wire send attempt == one sent_sum credit: resends after a
        # connection death count again, keeping final <= sent_sum valid
        sent_sum += value
        try:
            rep = client.request(f"tenant{IDX}",
                                 f"n{IDX}-acct-{i % 4}", "add", value)
        except (OSError, ConnectionError, socket.timeout):
            counts["conn_error"] += 1
            client.close()
            time.sleep(0.2)
            continue
        if rep.get("status") == "ok":
            acked_sum += value
            counts["ok"] += 1
        elif rep.get("status") == "shed":
            counts["shed"] += 1
            time.sleep(min(1.0, rep.get("retry_after_ms", 100) / 1e3))
        else:
            counts["error"] += 1
        time.sleep(0.01)
    client.close()
    node_barrier("load_done", timeout=dilated(240.0))
    node_result({"role": "load", "sent_sum": sent_sum,
                 "acked_sum": acked_sum, **counts})

node_barrier("done", timeout=dilated(120.0))
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 3, timeout=900.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23710"})
    chaos = results[0]
    loads = [results[1], results[2]]
    assert chaos["role"] == "chaos"
    # every chaos leg executed through the front door
    assert chaos["legs"] == {"rebalance": "ok", "crash_restore": "ok",
                             "failover": "ok"}, chaos["legs"]

    sent = sum(r["sent_sum"] for r in loads)
    acked = sum(r["acked_sum"] for r in loads)
    final = chaos["final_total"]
    # clients actually exercised the gateway across the outages
    assert all(r["ok"] > 0 for r in loads), loads
    assert acked > 0
    # THE conserved-value invariant: no acknowledged write lost (WAL +
    # snapshot + replay), nothing conjured beyond what was sent
    assert acked - 1e-6 <= final <= sent + 1e-6, \
        f"acked={acked} final={final} sent={sent}"

    # the SLO artifact came out of the run with the stable schema
    art = chaos["artifact"]
    for key in ("p50_ms", "p99_ms", "reject_rate", "requests",
                "error_budget_remaining"):
        assert key in art, art
    assert art["requests"] > 0


def test_gateway_durable_entities_survive_kill9():
    """ISSUE 15 acceptance: kill -9 a DURABLE gateway (entity journal +
    remember-entities store armed) under load, restart with --restore,
    and the restarted region respawns every remembered entity with its
    exact acked state — per-entity AND globally:

        last_acked_reply(e) <= final(e) <= sent(e)
        acked_sum <= final_total <= sent_sum

    The left bound is the new durable guarantee (zero lost acked writes
    at ENTITY granularity — the WAL-only path guaranteed it only for the
    conserved sum); the remember-entities respawn is visible through the
    `durable` admin op before post-restore traffic can recreate ids."""
    worker = _COMMON + r"""
system = make_system()
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot", timeout=dilated(120.0))
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 3, 60, "3 members Up")
node_barrier("converged", timeout=dilated(120.0))

if IDX == 0:
    # --------------------------------------- gateway supervisor + kill -9
    if os.path.exists(STOP_FILE):
        os.remove(STOP_FILE)
    gw_dir = tempfile.mkdtemp(prefix="gw_durable_")
    serve = spawn_serve(gw_dir, durable=True)
    node_barrier("gw_up", timeout=dilated(180.0))
    admin = GatewayClient("127.0.0.1", GW_PORT, timeout=30.0)

    time.sleep(dilated(3.0))   # acked traffic group-commits the journal
    serve.send_signal(signal.SIGKILL)
    serve.wait()
    admin.close()
    serve = spawn_serve(gw_dir, restore=True, durable=True)
    # respawn evidence straight after READY: replayed_entities was fixed
    # at restore time, before the port opened, so load racing back in
    # cannot have created it
    dur = admin.request_retry("__admin", "", "durable",
                              deadline_s=dilated(60.0))["data"]

    time.sleep(dilated(3.0))   # post-restore traffic on respawned rows
    open(STOP_FILE, "w").close()
    node_barrier("load_done", timeout=dilated(240.0))

    final = admin.request_retry("__admin", "", "sum",
                                deadline_s=dilated(60.0))
    by_entity = {}
    for n in (1, 2):
        for k in range(4):
            e = f"n{n}-acct-{k}"
            rep = admin.request_retry(f"tenant{n}", e, "get", 0.0,
                                      deadline_s=dilated(60.0))
            if rep.get("status") == "ok":
                by_entity[e] = float(rep["value"])
    admin.close()
    serve.send_signal(signal.SIGTERM)
    try:
        serve.wait(timeout=dilated(30.0))
    except subprocess.TimeoutExpired:
        serve.kill()
    os.remove(STOP_FILE)
    node_result({"role": "chaos", "durable": dur,
                 "final_total": float(final["value"]),
                 "by_entity": by_entity})
else:
    # ------------------------------------------- sustained-load client
    node_barrier("gw_up", timeout=dilated(180.0))
    client = GatewayClient("127.0.0.1", GW_PORT, timeout=10.0)
    sent_sum = acked_sum = 0.0
    sent_by = {}
    last_acked = {}
    counts = {"ok": 0, "shed": 0, "error": 0, "conn_error": 0}
    i = 0
    while not os.path.exists(STOP_FILE):
        i += 1
        value = float(i % 5 + 1)
        entity = f"n{IDX}-acct-{i % 4}"
        sent_sum += value
        sent_by[entity] = sent_by.get(entity, 0.0) + value
        try:
            rep = client.request(f"tenant{IDX}", entity, "add", value)
        except (OSError, ConnectionError, socket.timeout):
            counts["conn_error"] += 1
            client.close()
            time.sleep(0.2)
            continue
        if rep.get("status") == "ok":
            acked_sum += value
            counts["ok"] += 1
            # the ok reply carries the post-add running total: the last
            # one per entity is that entity's acked frontier floor
            last_acked[entity] = float(rep["value"])
        elif rep.get("status") == "shed":
            counts["shed"] += 1
            time.sleep(min(1.0, rep.get("retry_after_ms", 100) / 1e3))
        else:
            counts["error"] += 1
        time.sleep(0.01)
    client.close()
    node_barrier("load_done", timeout=dilated(240.0))
    node_result({"role": "load", "sent_sum": sent_sum,
                 "acked_sum": acked_sum, "sent_by": sent_by,
                 "last_acked": last_acked, **counts})

node_barrier("done", timeout=dilated(120.0))
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 3, timeout=900.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23810"})
    chaos = results[0]
    loads = [results[1], results[2]]
    assert chaos["role"] == "chaos"

    # the durable layer was armed and the restart respawned remembered
    # entities from the store + journal, not from traffic
    dur = chaos["durable"]
    assert dur["attached"], dur
    assert dur["remembered"] == 8, dur       # 2 load nodes x 4 accounts
    assert dur["replayed_entities"] >= 1, dur
    assert dur["journal"]["entities"] >= 1, dur

    sent = sum(r["sent_sum"] for r in loads)
    acked = sum(r["acked_sum"] for r in loads)
    final = chaos["final_total"]
    assert all(r["ok"] > 0 for r in loads), loads
    assert acked > 0
    # global conserved-value invariant: ZERO lost acked writes
    assert acked - 1e-6 <= final <= sent + 1e-6, \
        f"acked={acked} final={final} sent={sent}"

    # per-entity durable exactness: every entity's final state holds at
    # least everything its client was acknowledged, and no more than it
    # ever sent (floats are small integer sums here, so 1e-6 is slack)
    by_entity = chaos["by_entity"]
    for r in loads:
        for e, floor in r["last_acked"].items():
            assert e in by_entity, (e, by_entity)
            assert floor - 1e-6 <= by_entity[e] <= r["sent_by"][e] + 1e-6, \
                (e, floor, by_entity[e], r["sent_by"][e])


def test_gateway_exactly_once_retry_effects():
    """ISSUE 20 acceptance: with idempotent client sessions (every retry
    resends the SAME request id) against a --durable --dedup gateway,
    the conserved-value INEQUALITY upgrades to exact equality

        final_total == intended_sum

    under murmur3-chosen losses at every point in a request's life —
    pre-send (connection dies before the frame leaves), in-flight (frame
    sent, reply lost), post-ack (ack received but "lost", the client
    resends anyway) — plus two kill -9 + --restore legs that land server
    kills around the journal commit (post-commit-pre-ack included). Each
    intent's value counts into intended_sum exactly ONCE no matter how
    many wire attempts it took; duplicates are replayed from the
    journaled reply cache, never re-applied.

    The dedup-off CONTROL leg then demonstrates the bug the cache
    removes: the same lost-ack resend against a plain --durable gateway
    double-applies (>= 1 duplicate, final == 2x the intended value)."""
    worker = _COMMON + r"""
from akka_tpu.gateway.ingress import encode_frame
from akka_tpu.testkit.chaos import chaos_hit_np

system = make_system()
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot", timeout=dilated(120.0))
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 3, 60, "3 members Up")
node_barrier("converged", timeout=dilated(120.0))

if IDX == 0:
    # ------------------------------- gateway supervisor + kill -9 legs
    if os.path.exists(STOP_FILE):
        os.remove(STOP_FILE)
    gw_dir = tempfile.mkdtemp(prefix="gw_exact_")
    serve = spawn_serve(gw_dir, durable=True, dedup=True)
    node_barrier("gw_up", timeout=dilated(180.0))
    admin = GatewayClient("127.0.0.1", GW_PORT, timeout=30.0)

    # two kill legs: SIGKILL lands wherever the serve path happens to
    # be — including between the journal fsync and the ack reaching
    # the wire (post-commit-pre-ack), the loss only the JOURNALED
    # reply cache survives exactly-once
    for leg in range(2):
        time.sleep(dilated(3.0))
        serve.send_signal(signal.SIGKILL)
        serve.wait()
        admin.close()
        serve = spawn_serve(gw_dir, restore=True, durable=True,
                            dedup=True)

    time.sleep(dilated(3.0))   # post-restore traffic through the cache
    open(STOP_FILE, "w").close()
    node_barrier("load_done", timeout=dilated(240.0))

    final = admin.request_retry("__admin", "", "sum",
                                deadline_s=dilated(60.0))
    stats = admin.request_retry("__admin", "", "stats",
                                deadline_s=dilated(60.0))["data"]
    admin.close()
    serve.send_signal(signal.SIGTERM)
    try:
        serve.wait(timeout=dilated(30.0))
    except subprocess.TimeoutExpired:
        serve.kill()

    # ---- dedup-off CONTROL: the duplicate the cache removes. A
    # lost-ack resend of the SAME id double-applies without it.
    ctl_dir = tempfile.mkdtemp(prefix="gw_ctl_")
    ctl = spawn_serve(ctl_dir, durable=True)
    cc = GatewayClient("127.0.0.1", GW_PORT, timeout=30.0)
    creq = {"id": cc._next_id(), "tenant": "ctl", "entity": "ctl-acct",
            "op": "add", "value": 7.0}
    r1 = cc._request_raw(creq)
    cc.close()                 # the ack "was lost": reconnect, resend
    r2 = cc._request_raw(creq)
    rg = cc.request("ctl", "ctl-acct", "get", 0.0)
    cc.close()
    ctl.send_signal(signal.SIGTERM)
    try:
        ctl.wait(timeout=dilated(30.0))
    except subprocess.TimeoutExpired:
        ctl.kill()
    os.remove(STOP_FILE)
    node_result({"role": "chaos", "final_total": float(final["value"]),
                 "dedup": stats.get("dedup", {}),
                 "control": {"first": r1.get("value"),
                             "second": r2.get("value"),
                             "second_dedup": bool(r2.get("dedup")),
                             "total": rg.get("value")}})
else:
    # -------------------- idempotent-session client with chosen losses
    node_barrier("gw_up", timeout=dilated(180.0))
    client = GatewayClient("127.0.0.1", GW_PORT, timeout=10.0)
    SEED = 0xE0E0 + IDX
    intended_sum = 0.0
    counts = {"ok": 0, "shed_waits": 0, "conn_error": 0,
              "pre_send": 0, "mid_flight": 0, "post_ack": 0,
              "dedup_replays": 0, "failed_intents": 0}
    i = 0
    while not os.path.exists(STOP_FILE):
        i += 1
        value = float(i % 5 + 1)
        # one INTENT counts once, however many wire attempts it takes
        intended_sum += value
        req = {"id": client._next_id(), "tenant": f"tenant{IDX}",
               "entity": f"n{IDX}-acct-{i % 4}", "op": "add",
               "value": value}
        # murmur3-chosen loss schedule (testkit.chaos): one lane per
        # loss point in the request's life
        if bool(chaos_hit_np(SEED, i, 0, 0.06)):      # pre-send
            counts["pre_send"] += 1
            client.close()
        if bool(chaos_hit_np(SEED, i, 1, 0.06)):      # in-flight
            counts["mid_flight"] += 1
            try:
                if client._sock is None:
                    client.connect()
                client._sock.sendall(encode_frame(req))
            except (OSError, ConnectionError, socket.timeout):
                pass
            client.close()   # reply lost; the server MAY have applied
        rep = None
        deadline = time.monotonic() + dilated(120.0)
        while time.monotonic() < deadline:
            try:
                rep = client._request_raw(req)   # SAME id, every attempt
            except (OSError, ConnectionError, socket.timeout):
                counts["conn_error"] += 1
                client.close()
                time.sleep(0.2)
                continue
            if rep.get("status") == "shed":
                counts["shed_waits"] += 1
                time.sleep(min(1.0, rep.get("retry_after_ms", 100) / 1e3))
                continue
            break
        if rep is None or rep.get("status") != "ok":
            counts["failed_intents"] += 1
            continue
        counts["ok"] += 1
        if rep.get("dedup"):
            counts["dedup_replays"] += 1
        if bool(chaos_hit_np(SEED, i, 2, 0.06)):      # post-ack lost ack
            counts["post_ack"] += 1
            try:
                rep2 = client._request_raw(req)
                if rep2.get("dedup"):
                    counts["dedup_replays"] += 1
            except (OSError, ConnectionError, socket.timeout):
                client.close()
        time.sleep(0.01)
    client.close()
    node_barrier("load_done", timeout=dilated(240.0))
    node_result({"role": "load", "intended_sum": intended_sum, **counts})

node_barrier("done", timeout=dilated(120.0))
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 3, timeout=900.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23910"})
    chaos = results[0]
    loads = [results[1], results[2]]
    assert chaos["role"] == "chaos"

    # every intent resolved to an ok ack and every loss lane fired
    for r in loads:
        assert r["ok"] > 0 and r["failed_intents"] == 0, r
        assert r["pre_send"] > 0 and r["mid_flight"] > 0 \
            and r["post_ack"] > 0, r
    assert sum(r["dedup_replays"] for r in loads) >= 1, loads

    # THE exactly-once invariant: intended == final EXACTLY — no lost
    # acked write (journal) and no double-applied retry (reply cache),
    # across two kill -9 legs and every client-side loss lane
    intended = sum(r["intended_sum"] for r in loads)
    final = chaos["final_total"]
    assert abs(final - intended) <= 1e-6, \
        f"intended={intended} final={final} diff={final - intended}"

    # the server-side cache actually served duplicates
    dd = chaos["dedup"]
    assert dd.get("hits", 0) + dd.get("alias_hits", 0) >= 1, dd

    # dedup-off control: the SAME lost-ack resend double-applies —
    # >= 1 duplicate, demonstrating the bug the tentpole removes
    ctl = chaos["control"]
    assert ctl["first"] == pytest.approx(7.0), ctl
    assert ctl["second"] == pytest.approx(14.0), ctl   # re-applied!
    assert not ctl["second_dedup"], ctl
    assert ctl["total"] == pytest.approx(14.0), ctl
