"""Serving gateway (akka_tpu/gateway): admission, SLO tracking, framed-TCP
ingress onto sharded device entities, the typed AskPoolExhausted fast-fail,
and the tell-WAL group-commit knob.

Tier-1 scope: unit tests run hostside; the in-proc smoke drives the real
handle_frame -> region-ask path on the virtual CPU mesh; the TCP tests use
a real loopback socket through the stream layer. The multi-process chaos
tier lives in tests/test_gateway_chaos.py (slow)."""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from akka_tpu import ActorSystem
from akka_tpu.gateway import (AdmissionController, AskPoolExhausted,
                              FrameReader, GatewayClient, GatewayServer,
                              Reject, RegionBackend, SloTracker, TokenBucket,
                              counter_behavior, encode_frame)
from akka_tpu.gateway.ingress import encode_body


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------- admission
def test_token_bucket_burst_then_refill():
    clk = FakeClock()
    b = TokenBucket(rate=10.0, burst=3.0, clock=clk)
    assert [b.try_acquire() for _ in range(4)] == [True, True, True, False]
    clk.advance(0.1)  # one token refilled
    assert b.try_acquire()
    assert not b.try_acquire()
    clk.advance(100.0)  # refill caps at burst
    assert [b.try_acquire() for _ in range(4)] == [True, True, True, False]


def test_token_bucket_retry_after():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=1.0, clock=clk)
    assert b.try_acquire()
    # 1 token missing at 2/s -> 0.5s
    assert b.retry_after() == pytest.approx(0.5)


def test_admission_rate_limit_is_per_tenant():
    clk = FakeClock()
    a = AdmissionController(rate=0.0, burst=2.0, clock=clk)
    assert a.admit("t0") is None
    assert a.admit("t0") is None
    rej = a.admit("t0")
    assert isinstance(rej, Reject) and rej.reason == "rate_limited"
    # t1 has its own bucket: t0 flooding does not starve it
    assert a.admit("t1") is None
    assert a.rejected_by_reason == {"rate_limited": 1}
    assert a.admitted == 3


def test_admission_pressure_shed_and_cooldown_recovery():
    clk = FakeClock()
    sig = {"v": 0.0}
    a = AdmissionController(rate=1e9, burst=1e9,
                            pressure_signals={"boom": lambda: sig["v"]},
                            thresholds={"boom": 1.0},
                            check_interval_s=0.0, cooldown_s=5.0, clock=clk)
    assert a.admit("t0") is None
    sig["v"] = 2.0  # above threshold: everyone sheds, typed reason
    rej = a.admit("t0")
    assert rej is not None and rej.reason == "overloaded:boom"
    assert rej.retry_after_s > 0
    assert a.admit("other-tenant") is not None  # shed is global
    sig["v"] = 0.0
    clk.advance(1.0)  # signal recovered but cooldown (hysteresis) holds
    assert a.admit("t0") is not None
    clk.advance(10.0)
    assert a.admit("t0") is None
    st = a.stats()
    assert st["overloaded"] == 0 and st["signal_boom"] == 0.0


def test_admission_ask_pool_exhausted_arms_cooldown():
    clk = FakeClock()
    a = AdmissionController(rate=1e9, burst=1e9, cooldown_s=2.0, clock=clk)
    assert a.admit("t0") is None
    a.note_ask_pool_exhausted()  # instantly observed, no poll latency
    rej = a.admit("t0")
    assert rej is not None and rej.reason == "overloaded:ask_pool_exhausted"
    clk.advance(3.0)
    assert a.admit("t0") is None


def test_admission_dead_signal_does_not_take_down_ingress():
    def boom():
        raise RuntimeError("collector died")

    a = AdmissionController(rate=1e9, burst=1e9,
                            pressure_signals={"dead": boom},
                            thresholds={"dead": 0.0}, check_interval_s=0.0)
    assert a.admit("t0") is None


# --------------------------------------------------------------- wire codec
def test_frame_codec_roundtrip_and_partials():
    msgs = [{"id": i, "op": "add", "value": float(i)} for i in range(5)]
    blob = b"".join(encode_frame(m) for m in msgs)
    # byte-at-a-time reassembly
    r = FrameReader()
    out = []
    for i in range(len(blob)):
        out.extend(r.feed(blob[i:i + 1]))
    assert out == msgs
    # all frames in one feed
    assert list(FrameReader().feed(blob)) == msgs


def test_frame_reader_rejects_oversize_frame():
    r = FrameReader(max_frame=16)
    with pytest.raises(ValueError, match="exceeds"):
        list(r.feed(encode_frame({"pad": "x" * 64})))


# ---------------------------------------------------------------------- slo
def test_slo_tracker_artifact_schema_and_budget():
    slo = SloTracker(target_p50_ms=100.0, target_p99_ms=100.0,
                     slo_target=0.9)
    for ms in (10, 20, 30, 40):
        slo.record("t0", "ok", latency_s=ms / 1e3)
    slo.record("t1", "reject")
    slo.record("t0", "timeout", latency_s=5.0)
    art = slo.artifact()
    assert art["requests"] == 6 and art["ok"] == 4
    assert art["rejects"] == 1 and art["timeouts"] == 1
    # rejects are NOT SLO violations: budget denominator is served traffic
    assert art["error_budget_total"] == pytest.approx(0.1 * 5)
    assert art["error_budget_spent"] == 1
    assert art["reject_rate"] == pytest.approx(1 / 6, abs=1e-3)
    assert art["per_tenant"]["t0"]["ok"] == 4
    assert art["per_tenant"]["t1"]["reject"] == 1
    assert art["p50_met"] == 1 and art["p99_met"] == 0
    for key in ("p50_ms", "p99_ms", "target_p50_ms", "target_p99_ms",
                "slo_target", "error_budget_remaining", "step"):
        assert key in art


def test_slo_percentiles_nearest_rank():
    slo = SloTracker()
    for ms in range(1, 101):
        slo.record("t", "ok", latency_s=ms / 1e3)
    assert slo.percentile(0.50) == pytest.approx(50.0)
    assert slo.percentile(0.99) == pytest.approx(99.0)
    assert slo.percentile(1.00) == pytest.approx(100.0)


def test_slo_unknown_outcome_rejected():
    with pytest.raises(ValueError):
        SloTracker().record("t", "dropped")


# ------------------------------------------------------- WAL group commit
def _fill(journal, n=10):
    for i in range(n):
        journal.append(i, "tell", np.asarray([i], np.int32),
                       np.asarray([[float(i)] * 4], np.float32),
                       np.asarray([0], np.int32))


def test_tell_journal_fsync_every_n_bit_identical(tmp_path):
    from akka_tpu.persistence.tell_journal import TellJournal
    a = TellJournal(str(tmp_path / "a.wal"), fsync_every_n=1)
    b = TellJournal(str(tmp_path / "b.wal"), fsync_every_n=8)
    _fill(a), _fill(b)
    a.close(), b.close()
    assert (tmp_path / "a.wal").read_bytes() == \
        (tmp_path / "b.wal").read_bytes()


def test_tell_journal_group_commit_crash_at_batch_boundary(tmp_path):
    """kill -9 inside a group-commit window: every flushed record before
    the torn tail survives; the torn record is truncated away on reopen
    (repair_record_log), exactly as with per-record fsync."""
    from akka_tpu.persistence.tell_journal import TellJournal
    path = str(tmp_path / "j.wal")
    j = TellJournal(path, fsync_every_n=8)
    _fill(j, 10)
    assert j._since_fsync == 2  # mid-window: 2 records past the last fsync
    j._fh.flush()
    # simulate the crash mid-append: tear the last record's tail
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    j._fh.close()  # drop the writer without close() (no final fsync)

    j2 = TellJournal(path, fsync_every_n=8)
    recs = list(j2.records())
    assert len(recs) == 9  # 9 intact, the torn 10th truncated
    assert [int(r["step"]) for r in recs] == list(range(9))
    assert j2.truncated_bytes > 0
    # the journal stays appendable after repair
    j2.append(99, "tell", np.asarray([0], np.int32),
              np.asarray([[1.0] * 4], np.float32), np.asarray([0], np.int32))
    j2.sync()
    assert [int(r["step"]) for r in j2.records()][-1] == 99
    j2.close()


def test_tell_journal_sync_and_close_flush_pending(tmp_path):
    from akka_tpu.persistence.tell_journal import TellJournal
    j = TellJournal(str(tmp_path / "j.wal"), fsync_every_n=100)
    _fill(j, 3)
    assert j._since_fsync == 3
    j.sync()
    assert j._since_fsync == 0
    _fill(j, 2)
    j.close()  # close fsyncs the pending window
    j2 = TellJournal(str(tmp_path / "j.wal"))
    assert len(list(j2.records())) == 5
    j2.close()


# -------------------------------------------- typed ask-pool fast-fail
BRIDGE_CFG = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                       "persistence": {"tell-journal": {"fsync-every-n": 4}},
                       "actor": {"tpu-dispatcher": {
                           "capacity": 256, "payload-width": 4,
                           "mailbox-slots": 4, "promise-rows": 1}}}}


def test_bridge_promise_rows_config_wiring():
    """promise-rows and the WAL group-commit key flow through the
    tpu-batched dispatcher config to the runtime handle (no device
    build needed — the handle carries the knobs before first spawn)."""
    from akka_tpu.batched.bridge import get_handle

    system = ActorSystem.create("gw-cfgwire", BRIDGE_CFG)
    try:
        h = get_handle(system)
        assert h.wal_fsync_every_n == 4
        assert h.promise_rows_n == 1
    finally:
        system.terminate()
        system.await_termination(10.0)


@pytest.mark.slow
def test_bridge_promise_rows_typed_exhaustion():
    """Draining the pool fast-fails with AskPoolExhausted (typed — the
    shed signal), not a timeout. Slow tier: spawning the device actor
    compiles the bridge runtime (~13s); the tier-1 region-level twin
    (test_region_typed_exhaustion_and_stats) keeps the typed error
    covered cheaply."""
    import jax.numpy as jnp
    from akka_tpu.batched import Emit, Mailbox, behavior, device_props
    from akka_tpu.batched.bridge import get_handle

    @behavior("silent", {"count": ((), jnp.float32)}, inbox="slots")
    def silent(state, mailbox: Mailbox, ctx):  # never replies
        got = mailbox.fold(jnp.asarray(0.0, jnp.float32),
                           lambda c, t, pl: c + pl[0])
        return ({"count": state["count"] + got}, Emit.none(1, 4))

    system = ActorSystem.create("gw-exhaust", BRIDGE_CFG)
    try:
        ref = system.actor_of(device_props(silent), "s1")
        h = get_handle(system)
        # satellite wiring: the system-wide WAL group-commit key reached
        # the handle through the dispatcher
        assert h.wal_fsync_every_n == 4
        assert h.promise_rows_n == 1
        f1 = h.ask(ref.row, (0, [1.0]), timeout=30.0)  # claims the only row
        f2 = h.ask(ref.row, (0, [1.0]), timeout=30.0)  # pool empty: typed
        with pytest.raises(AskPoolExhausted, match="promise rows exhausted"):
            f2.result(5.0)
        assert not f1.done()  # the in-flight ask is untouched
        st = h.ask_pool_stats()
        assert st["size"] == 1 and st["free"] == 0
        assert st["exhausted"] == 1 and st["occupancy"] == 1.0
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_region_typed_exhaustion_and_stats():
    from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion
    spec = DeviceEntity("exh", counter_behavior(4), n_shards=2,
                        entities_per_shard=4, n_devices=1, payload_width=4)
    region = DeviceShardRegion(spec)
    region._ensure_promise_rows()
    with region._lock:
        parked, region._promise_free = region._promise_free, []
    try:
        with pytest.raises(AskPoolExhausted, match="promise rows exhausted"):
            region.ask(0, 0, [1.0])
        st = region.ask_pool_stats()
        assert st["free"] == 0 and st["occupancy"] == 1.0
        assert st["exhausted"] == 1
    finally:
        with region._lock:
            region._promise_free = parked


# ------------------------------------------------------ in-proc gateway
def _req(server, tenant, entity, op, value=0.0, rid=1):
    body = encode_body({"id": rid, "tenant": tenant, "entity": entity,
                        "op": op, "value": value})
    return json.loads(server.handle_frame(body))


@pytest.fixture(scope="module")
def small_region():
    from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion
    spec = DeviceEntity("gwc", counter_behavior(4), n_shards=2,
                        entities_per_shard=8, n_devices=2, payload_width=4)
    return DeviceShardRegion(spec)


def test_gateway_inproc_smoke_below_threshold(small_region):
    """Below the rate threshold: requests flow, rejects ~ 0, totals exact."""
    slo = SloTracker()
    adm = AdmissionController(rate=1e6, burst=1e6)
    srv = GatewayServer(None, RegionBackend(small_region), adm, slo)
    base = RegionBackend(small_region).sum_all()
    total = 0.0
    for i in range(12):
        v = float(i % 3 + 1)
        total += v
        rep = _req(srv, "t0", f"acct-{i % 4}", "add", v, rid=i)
        assert rep["status"] == "ok", rep
    assert _req(srv, "t0", "acct-0", "get")["value"] == \
        pytest.approx(1 + 2 + 3)  # i = 0, 4, 8 -> values 1, 2, 3
    assert RegionBackend(small_region).sum_all() == \
        pytest.approx(base + total)
    art = slo.artifact()
    assert art["rejects"] == 0 and art["ok"] == 13
    assert art["p50_ms"] > 0


def test_gateway_inproc_sheds_at_overload(small_region):
    """Above the rate threshold the admission layer SHEDS (typed reject
    replies with retry_after), it does not let requests pile into
    timeouts."""
    slo = SloTracker()
    adm = AdmissionController(rate=1.0, burst=3.0)
    srv = GatewayServer(None, RegionBackend(small_region), adm, slo)
    statuses = [_req(srv, "t0", "acct-x", "add", 1.0, rid=i)["status"]
                for i in range(10)]
    assert statuses.count("ok") >= 3
    sheds = [s for s in statuses if s == "shed"]
    assert sheds, statuses
    rep = _req(srv, "t0", "acct-x", "add", 1.0, rid=99)
    assert rep["status"] == "shed" and rep["reason"] == "rate_limited"
    assert rep["retry_after_ms"] > 0
    art = slo.artifact()
    assert art["rejects"] == len(sheds) + 1
    assert art["reject_rate"] > 0
    # rejects spent no error budget
    assert art["error_budget_spent"] == 0


def test_gateway_inproc_admin_and_errors(small_region):
    slo = SloTracker()
    srv = GatewayServer(None, RegionBackend(small_region),
                        AdmissionController(rate=1e6, burst=1e6), slo)
    assert _req(srv, "__admin", "", "sum")["status"] == "ok"
    st = _req(srv, "__admin", "", "stats")["data"]
    assert "admission" in st and "region" in st and "ask_pool" in st
    art = _req(srv, "__admin", "", "artifact")["data"]
    assert "p99_ms" in art and "reject_rate" in art
    # typed errors, not dropped connections
    assert _req(srv, "t0", "e", "frobnicate")["reason"] == \
        "unknown_op:frobnicate"
    bad = json.loads(srv.handle_frame(b"{not json"))
    assert bad["status"] == "error" and \
        bad["reason"].startswith("bad_request:")
    assert _req(srv, "__admin", "", "nope")["reason"] == \
        "unknown_admin_op:nope"


def test_gateway_missing_entity_is_typed_and_not_charged():
    """A frame with no `entity` key fast-fails BEFORE admission: typed
    bad_request reply, the tenant's token bucket is never charged, and
    the backend never sees the frame (previously this admitted, then
    surfaced as fault:KeyError)."""
    class NeverBackend:
        def ask(self, entity_id, value):
            raise AssertionError("backend must not see a malformed frame")

    adm = AdmissionController(rate=1e6, burst=1e6)
    slo = SloTracker()
    srv = GatewayServer(None, NeverBackend(), adm, slo)
    body = encode_body({"id": 7, "tenant": "t0", "op": "add", "value": 1.0})
    rep = json.loads(srv.handle_frame(body))
    assert rep["status"] == "error"
    assert rep["reason"] == "bad_request:missing_entity"
    assert adm.admitted == 0
    assert slo.artifact()["errors"] == 1


def test_gateway_fault_leg_records_latency():
    """Generic backend faults record their latency like the timeout leg
    always did, so error-leg p99s stay honest in the SLO artifact."""
    class SlowBoom:
        def ask(self, entity_id, value):
            time.sleep(0.005)
            raise RuntimeError("boom")

    slo = SloTracker()
    srv = GatewayServer(None, SlowBoom(),
                        AdmissionController(rate=1e6, burst=1e6), slo)
    rep = _req(srv, "t0", "e", "add", 1.0)
    assert rep["status"] == "error" and rep["reason"] == "fault:RuntimeError"
    art = slo.artifact()
    assert art["errors"] == 1
    assert art["p50_ms"] >= 4.0  # the fault's ~5ms landed in the window


def test_gateway_ask_pool_exhaustion_becomes_shed(small_region):
    """The typed AskPoolExhausted fast-fail surfaces as a shed reply AND
    arms the admission cooldown (subsequent requests shed without touching
    the backend)."""
    class ExhaustedBackend:
        def ask(self, entity_id, value):
            raise AskPoolExhausted("promise rows exhausted (test)")

    clk = FakeClock()
    adm = AdmissionController(rate=1e6, burst=1e6, cooldown_s=5.0, clock=clk)
    srv = GatewayServer(None, ExhaustedBackend(), adm, SloTracker())
    rep = _req(srv, "t0", "acct", "add", 1.0)
    assert rep["status"] == "shed" and rep["reason"] == "ask_pool_exhausted"
    rep2 = _req(srv, "t0", "acct", "add", 1.0, rid=2)
    assert rep2["status"] == "shed"
    assert rep2["reason"] == "overloaded:ask_pool_exhausted"


# ------------------------------------------------------------ TCP ingress
def _mk_system(name):
    return ActorSystem(name, {"akka": {"stdout-loglevel": "OFF",
                                       "log-dead-letters": 0}})


def test_gateway_tcp_roundtrip(small_region):
    system = _mk_system("gw-tcp")
    try:
        srv = GatewayServer(system, RegionBackend(small_region),
                            AdmissionController(rate=1e6, burst=1e6),
                            SloTracker())
        host, port = srv.start()
        client = GatewayClient(host, port)
        try:
            base = float(client.admin("sum")["value"])
            assert client.request("t9", "tcp-acct", "add", 2.5)["status"] \
                == "ok"
            rep = client.request("t9", "tcp-acct", "add", 1.5)
            assert rep["status"] == "ok" and rep["value"] == \
                pytest.approx(4.0)
            assert client.request("t9", "tcp-acct", "get")["value"] == \
                pytest.approx(4.0)
            assert float(client.admin("sum")["value"]) == \
                pytest.approx(base + 4.0)
        finally:
            client.close()
            srv.stop()
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_gateway_slow_consumer_backpressure():
    """Satellite: a stalled TCP consumer throttles the producer through
    the ack-gated write path — processing PLATEAUS below the request
    count instead of buffering every reply — then resumes cleanly with
    zero loss and intact ordering once the consumer drains."""
    system = _mk_system("gw-bp")
    N, OP = 240, "x" * 30000  # unknown op -> ~30KB echo reply, no backend
    slo = SloTracker()
    srv = GatewayServer(system, None,
                        AdmissionController(rate=1e9, burst=1e9), slo,
                        max_frame=1 << 16)
    try:
        host, port = srv.start()
        # a tiny receive buffer (set BEFORE connect so the advertised
        # window honors it) makes the stall visible fast: the server can
        # park at most rcvbuf+sndbuf bytes in the kernel
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        # generous per-recv deadline: a saturated full-suite box can
        # starve the drain loop for tens of seconds without anything
        # being wrong — only a DEAD connection should fail the test
        sock.settimeout(120.0)
        sock.connect((host, port))
        blob = b"".join(
            encode_frame({"id": i, "tenant": "t", "entity": "e", "op": OP})
            for i in range(N))
        sender = threading.Thread(target=sock.sendall, args=(blob,),
                                  daemon=True)
        sender.start()

        # stalled consumer: watch the server-side processed counter stop
        def processed():
            return slo.artifact()["requests"]

        last, stable_since = -1, time.monotonic()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            cur = processed()
            if cur != last:
                last, stable_since = cur, time.monotonic()
            elif cur > 0 and time.monotonic() - stable_since > 1.0:
                break  # plateaued: backpressure reached the producer
            time.sleep(0.05)
        plateau = processed()
        assert 0 < plateau < N, \
            f"no backpressure: {plateau}/{N} processed while stalled"

        # resume: drain everything — no drops, order preserved
        reader = FrameReader(max_frame=1 << 20)
        got = []
        sock.settimeout(120.0)
        while len(got) < N:
            data = sock.recv(65536)
            assert data, f"connection died after {len(got)}/{N} replies"
            got.extend(reader.feed(data))
        sender.join(timeout=60.0)
        assert not sender.is_alive()
        assert [g["id"] for g in got] == list(range(N))
        assert all(g["status"] == "error" and
                   g["reason"].startswith("unknown_op:") for g in got)
        assert processed() == N
        sock.close()
    finally:
        srv.stop()
        system.terminate()
        system.await_termination(10.0)
