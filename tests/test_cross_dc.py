"""Cross-DC membership (VERDICT r2 #9): DC-tagged members, per-DC leaders
and heartbeat rings, lower-rate cross-DC heartbeats, per-DC reaping/SBR.

Reference: akka-cluster/src/main/scala/akka/cluster/
CrossDcClusterHeartbeat.scala:39 (CrossDcHeartbeatSender — only the oldest
members of each DC monitor other DCs), MembershipState per-DC
leader/convergence. TPU mapping: one DC per slice/pod, DCN between."""

import time

import pytest

from akka_tpu import ActorSystem
from akka_tpu.cluster import Cluster, MemberStatus
from akka_tpu.remote.transport import InProcTransport
from akka_tpu.testkit import await_condition


def _cfg(dc):
    return {"akka": {"actor": {"provider": "cluster"},
                     "stdout-loglevel": "OFF", "log-dead-letters": 0,
                     "remote": {"transport": "inproc",
                                "canonical": {"hostname": "local",
                                              "port": 0}},
                     "cluster": {"gossip-interval": "0.05s",
                                 "leader-actions-interval": "0.05s",
                                 "unreachable-nodes-reaper-interval": "0.1s",
                                 "multi-data-center": {
                                     "self-data-center": dc,
                                     "cross-dc-connections": 2},
                                 "failure-detector": {
                                     "heartbeat-interval": "0.1s",
                                     "acceptable-heartbeat-pause": "2s"},
                                 "split-brain-resolver": {
                                     "active-strategy": "keep-majority",
                                     "stable-after": "1s"}}}}


def _up_count(cluster):
    return sum(1 for m in cluster.state.members
               if m.status is MemberStatus.UP)


@pytest.fixture()
def two_dc_cluster():
    InProcTransport.fault_injector.reset()
    systems = [ActorSystem.create(f"dc{'ab'[i // 2]}{i % 2}",
                                  _cfg("east" if i < 2 else "west"))
               for i in range(4)]
    clusters = [Cluster.get(s) for s in systems]
    seed = str(systems[0].provider.local_address)
    for c in clusters:
        c.join(seed)
    await_condition(lambda: all(_up_count(c) == 4 for c in clusters),
                    max_time=15.0,
                    message=f"4-node 2-DC cluster did not form: "
                            f"{[c.state for c in clusters]}")
    yield systems, clusters
    for s in systems:
        s.terminate()
    for s in systems:
        s.await_termination(10.0)
    InProcTransport.fault_injector.reset()


def _addr(s):
    return f"local:{s.provider.local_address.port}"


def test_reserved_dc_role_prefix_rejected():
    """Regression (r3 review): a user role with the reserved dc- prefix
    would make data_center ambiguous — refused when the Cluster extension
    initializes (the extension is lazy, so that is first Cluster.get)."""
    cfg = _cfg("east")
    cfg["akka"]["cluster"]["roles"] = ["dc-ops"]
    s = ActorSystem.create("dcbad", cfg)
    try:
        with pytest.raises(ValueError, match="dc-"):
            Cluster.get(s)
    finally:
        s.terminate()
        s.await_termination(10.0)


def test_data_center_deterministic_with_multiple_dc_roles():
    """Wire data is untrusted: multiple dc- roles resolve deterministically
    (sorted), never by set iteration order."""
    from akka_tpu.cluster.member import Member, UniqueAddress
    m = Member(UniqueAddress("akka://x@h:1", 1),
               roles=frozenset({"dc-zeta", "dc-alpha", "worker"}))
    assert m.data_center == "alpha"


def test_two_dc_cluster_forms_with_dc_tags(two_dc_cluster):
    systems, clusters = two_dc_cluster
    state = clusters[0].state
    dcs = sorted(m.data_center for m in state.members)
    assert dcs == ["east", "east", "west", "west"]
    # DC rides the roles set like the reference's dc- prefix
    assert any(r.startswith("dc-") for m in state.members for r in m.roles)


def test_per_dc_leaders(two_dc_cluster):
    systems, clusters = two_dc_cluster
    # each node's published leader is its OWN DC's leader
    east_leaders = {str(clusters[i].state.leader) for i in (0, 1)}
    west_leaders = {str(clusters[i].state.leader) for i in (2, 3)}
    assert len(east_leaders) == 1 and len(west_leaders) == 1
    assert east_leaders != west_leaders


def test_cross_dc_partition_does_not_down_anyone(two_dc_cluster):
    """A DCN partition between DCs marks the other side unreachable but
    must NOT down it — each DC stays independently healthy (per-DC SBR)."""
    systems, clusters = two_dc_cluster
    fi = InProcTransport.fault_injector
    for i in (0, 1):
        for j in (2, 3):
            fi.blackhole(_addr(systems[i]), _addr(systems[j]))
            fi.blackhole(_addr(systems[j]), _addr(systems[i]))
    # give reaping + SBR stable-after ample time to (wrongly) fire
    time.sleep(4.0)
    for c in clusters:
        assert len(c.state.members) == 4, c.state
        assert _up_count(c) == 4, c.state
    # heal: reachability recovers, nobody was removed
    fi.reset()
    await_condition(
        lambda: all(not c.state.unreachable for c in clusters),
        max_time=15.0, message="partition never healed")


def test_each_dc_reaps_its_own_unreachables(two_dc_cluster):
    """Kill one west node: WEST's SBR downs it and WEST's leader removes
    it; east keeps running and simply learns the removal via gossip."""
    systems, clusters = two_dc_cluster
    dead = systems[3]
    dead_addr = str(dead.provider.local_address)
    dead.provider.shutdown_transport()
    dead.terminate()
    assert dead.await_termination(10.0)

    await_condition(
        lambda: all(len(c.state.members) == 3 for c in clusters[:3]),
        max_time=25.0,
        message=f"dead west node never removed: "
                f"{[c.state for c in clusters[:3]]}")
    for c in clusters[:3]:
        assert dead_addr not in {m.address_str for m in c.state.members}
        assert _up_count(c) == 3
