"""Remote deployment + cluster-aware routers — modeled on the reference
multi-jvm specs (akka-remote-tests RemoteDeploymentSpec semantics,
akka-cluster/src/multi-jvm ClusterRoundRobinSpec; SURVEY.md §2.3, §2.4) run
over the in-proc transport."""

import time

import pytest

from akka_tpu import (Actor, ActorSystem, Deploy, Props, RemoteScope,
                      Terminated, ask_sync)
from akka_tpu.actor.deploy import Deployer, LocalScope, NO_SCOPE
from akka_tpu.cluster import (Cluster, ClusterRouterGroup,
                              ClusterRouterGroupSettings, ClusterRouterPool,
                              ClusterRouterPoolSettings, MemberStatus)
from akka_tpu.remote.deploy import (DaemonMsgCreate, mangle,
                                    register_deployable)
from akka_tpu.remote.transport import InProcTransport
from akka_tpu.routing.router import (Broadcast, GetRoutees, RoundRobinGroup,
                                     RoundRobinPool, Routees)
from akka_tpu.testkit import await_condition


def remote_system(name: str, extra=None) -> ActorSystem:
    cfg = {"akka": {"actor": {"provider": "remote"},
                    "stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "remote": {"transport": "inproc",
                               "canonical": {"hostname": "local", "port": 0}}}}
    if extra:
        cfg["akka"]["actor"].update(extra)
    return ActorSystem.create(name, cfg)


def addr_of(system) -> str:
    a = system.provider.local_address
    return f"akka://{system.name}@{a.host}:{a.port}"


@pytest.fixture()
def two_systems():
    InProcTransport.fault_injector.reset()
    a = remote_system("depA")
    b = remote_system("depB")
    yield a, b
    for s in (a, b):
        s.terminate()
    for s in (a, b):
        assert s.await_termination(10.0)
    InProcTransport.fault_injector.reset()


@register_deployable
class WhereAmI(Actor):
    def __init__(self, tag="?"):
        super().__init__()
        self.tag = tag

    def receive(self, message):
        if message == "where":
            self.sender.tell(
                (self.tag, str(self.context.system.name),
                 self.self_ref.path.to_serialization_format()),
                self.self_ref)
        elif message == "boom":
            raise RuntimeError("boom")
        else:
            self.sender.tell(("echo", message), self.self_ref)


# -- deployer config parsing --------------------------------------------------

def test_deployer_lookup_literal_and_wildcard():
    class S:
        pass

    from akka_tpu.config import Config
    s = S()
    s.config = Config({"akka": {"actor": {"deployment": {
        "/service": {"remote": "akka://other@h:1"},
        "/workers/*": {"dispatcher": "blocking-io-dispatcher"},
        "/pool": {"router": "round-robin-pool", "nr-of-instances": 3},
    }}}})
    d = Deployer(s)
    dep = d.lookup(["service"])
    assert dep is not None and dep.scope.address == "akka://other@h:1"
    assert d.lookup(["workers", "w7"]).dispatcher == "blocking-io-dispatcher"
    assert d.lookup(["pool"]).router_config.nr_of_instances == 3
    assert d.lookup(["nothing"]) is None
    assert d.lookup(["service", "child"]) is None


def test_deploy_with_fallback_merge():
    a = Deploy(scope=RemoteScope("akka://x@h:1"))
    b = Deploy(dispatcher="d1", scope=NO_SCOPE)
    merged = a.with_fallback(b)
    assert isinstance(merged.scope, RemoteScope)
    assert merged.dispatcher == "d1"


# -- programmatic remote deployment ------------------------------------------

def test_remote_deploy_via_props(two_systems):
    a, b = two_systems
    ref = a.actor_of(
        Props.create(WhereAmI, "t1").with_deploy(
            Deploy(scope=RemoteScope(addr_of(b)))),
        "worker")
    tag, sysname, path = ask_sync(ref, "where", timeout=5.0, system=a)
    assert tag == "t1"
    assert sysname == "depB"          # the actor RUNS on b
    assert "/remote/" in path          # under b's daemon
    assert "akka://depB" in path
    # ordinary messaging round-trips
    assert ask_sync(ref, 42, timeout=5.0, system=a) == ("echo", 42)


def test_remote_deploy_via_config(two_systems):
    InProcTransport.fault_injector.reset()
    b = remote_system("depB2")
    a = None
    try:
        a = remote_system("depA2", extra={
            "deployment": {"/cfg-worker": {"remote": addr_of(b)}}})
        ref = a.actor_of(Props.create(WhereAmI, "cfg"), "cfg-worker")
        tag, sysname, _ = ask_sync(ref, "where", timeout=5.0, system=a)
        assert (tag, sysname) == ("cfg", "depB2")
    finally:
        for s in (a, b):
            if s is not None:
                s.terminate()
                s.await_termination(10.0)


def test_remote_deployed_actor_watchable_and_stoppable(two_systems):
    a, b = two_systems
    ref = a.actor_of(
        Props.create(WhereAmI).with_deploy(Deploy(scope=RemoteScope(addr_of(b)))),
        "mortal")
    assert ask_sync(ref, "where", timeout=5.0, system=a)[1] == "depB"

    seen = []

    class Watcher(Actor):
        def pre_start(self):
            self.context.watch(ref)

        def receive(self, message):
            if isinstance(message, Terminated):
                seen.append(message.actor)

    a.actor_of(Props.create(Watcher), "watcher")
    time.sleep(0.2)
    ref.stop()
    await_condition(lambda: len(seen) == 1, max_time=5.0,
                    message="no Terminated for remote-deployed actor")


def test_remote_deploy_restarts_on_failure(two_systems):
    a, b = two_systems
    ref = a.actor_of(
        Props.create(WhereAmI, "sup").with_deploy(
            Deploy(scope=RemoteScope(addr_of(b)))),
        "crashy")
    assert ask_sync(ref, "where", timeout=5.0, system=a)[0] == "sup"
    ref.tell("boom")  # daemon supervision restarts it on b
    time.sleep(0.3)
    assert ask_sync(ref, "where", timeout=5.0, system=a)[0] == "sup"


def test_remote_deploy_requires_recipe(two_systems):
    a, b = two_systems
    with pytest.raises(Exception):
        a.actor_of(
            Props.from_factory(lambda: WhereAmI()).with_deploy(
                Deploy(scope=RemoteScope(addr_of(b)))),
            "norecipe")


def test_unregistered_class_is_refused(two_systems):
    a, b = two_systems

    class Local(Actor):  # not registered, defined inside a function
        def receive(self, message):
            self.sender.tell("hi", self.self_ref)

    # origin side ships the recipe fine (class_key computed), but the wire
    # codec itself refuses to encode args only when unregistered classes are
    # used; the target daemon must refuse to import unregistered keys.
    dead = []
    from akka_tpu.actor.messages import DeadLetter
    b.event_stream.subscribe(lambda e: dead.append(e), DeadLetter)
    ref = a.actor_of(
        Props.create(Local).with_deploy(Deploy(scope=RemoteScope(addr_of(b)))),
        "refused")
    time.sleep(0.3)
    # never instantiated on b: asks time out / dead-letter reported
    assert any("refusing to deploy" in repr(getattr(d, "message", ""))
               for d in dead)


def test_mangle_roundtrip_is_valid_path_element():
    from akka_tpu.actor.path import validate_path_element
    m = mangle("akka://sysA@local:1/user/worker#12345")
    validate_path_element(m)  # must not raise


# -- cluster-aware routers ----------------------------------------------------

CLUSTER_FAST = {"akka": {"actor": {"provider": "cluster"},
                         "stdout-loglevel": "OFF", "log-dead-letters": 0,
                         "remote": {"transport": "inproc",
                                    "canonical": {"hostname": "local",
                                                  "port": 0}},
                         "cluster": {"gossip-interval": "0.05s",
                                     "leader-actions-interval": "0.05s",
                                     "unreachable-nodes-reaper-interval": "0.2s",
                                     "failure-detector": {
                                         "heartbeat-interval": "0.1s",
                                         "acceptable-heartbeat-pause": "2s"}}}}


@pytest.fixture()
def two_node_cluster():
    InProcTransport.fault_injector.reset()
    systems = [ActorSystem.create(f"crt{i}", CLUSTER_FAST) for i in range(2)]
    clusters = [Cluster.get(s) for s in systems]
    seed = str(systems[0].provider.local_address)
    for c in clusters:
        c.join(seed)
    await_condition(
        lambda: all(sum(1 for m in c.state.members
                        if m.status is MemberStatus.UP) == 2
                    for c in clusters),
        max_time=10.0, message="2-node cluster did not form")
    yield systems, clusters
    for s in systems:
        s.terminate()
    for s in systems:
        s.await_termination(10.0)
    InProcTransport.fault_injector.reset()


def _routee_count(system, router_ref):
    return len(ask_sync(router_ref, GetRoutees(), timeout=5.0,
                        system=system).routees)


def test_cluster_router_pool_spans_nodes(two_node_cluster):
    systems, clusters = two_node_cluster
    a, b = systems
    router = a.actor_of(
        Props.create(WhereAmI, "pool").with_router(ClusterRouterPool(
            RoundRobinPool(0),
            ClusterRouterPoolSettings(total_instances=4,
                                      max_instances_per_node=2))),
        "span-pool")
    await_condition(lambda: _routee_count(a, router) == 4, max_time=10.0,
                    message="pool did not reach 4 routees over 2 nodes")
    # routees actually run on BOTH systems
    homes = set()
    for _ in range(8):
        _, sysname, _ = ask_sync(router, "where", timeout=5.0, system=a)
        homes.add(sysname)
    assert homes == {"crt0", "crt1"}


def test_cluster_router_pool_respects_roles(two_node_cluster):
    systems, clusters = two_node_cluster
    a, b = systems
    # nobody carries role "gpu" -> no routees beyond none
    router = a.actor_of(
        Props.create(WhereAmI).with_router(ClusterRouterPool(
            RoundRobinPool(0),
            ClusterRouterPoolSettings(total_instances=4,
                                      max_instances_per_node=2,
                                      use_roles=frozenset({"gpu"})))),
        "role-pool")
    time.sleep(0.5)
    assert _routee_count(a, router) == 0


def test_cluster_router_group_selects_remote_paths(two_node_cluster):
    systems, clusters = two_node_cluster
    a, b = systems
    # a service instance on each node at the same path
    for s in systems:
        s.actor_of(Props.create(WhereAmI, f"svc-{s.name}"), "svc")
    time.sleep(0.2)
    router = a.actor_of(
        Props.create(WhereAmI).with_router(ClusterRouterGroup(
            RoundRobinGroup(["/user/svc"]),
            ClusterRouterGroupSettings(total_instances=2,
                                       routees_paths=("/user/svc",)))),
        "span-group")
    await_condition(lambda: _routee_count(a, router) == 2, max_time=10.0,
                    message="group did not pick up both nodes")
    homes = set()
    for _ in range(6):
        tag, sysname, _ = ask_sync(router, "where", timeout=5.0, system=a)
        homes.add(sysname)
    assert homes == {"crt0", "crt1"}


@register_deployable
class SpawnerParent(Actor):
    """Spawns/stops a remote-deployed child named 'rc' on demand."""

    def __init__(self, remote_addr):
        super().__init__()
        self.remote_addr = remote_addr

    def receive(self, message):
        if message == "spawn":
            self.context.actor_of(
                Props.create(WhereAmI, "rc-child").with_deploy(
                    Deploy(scope=RemoteScope(self.remote_addr))), "rc")
            self.sender.tell("spawned")
        elif message == "stop-child":
            child = self.context.child("rc")
            if child is not None:
                self.context.stop(child)
            self.sender.tell("stopping")
        elif message == "has-child":
            self.sender.tell(self.context.child("rc") is not None)


def test_remote_child_name_freed_after_termination(two_systems):
    """ADVICE r2 (cell.py:143): a terminated remote-deployed child must leave
    _remote_children — the name becomes reusable instead of raising
    InvalidActorNameException forever."""
    a, b = two_systems
    parent = a.actor_of(Props.create(SpawnerParent, addr_of(b)), "sp-parent")
    assert ask_sync(parent, "spawn", timeout=5.0, system=a) == "spawned"
    assert ask_sync(parent, "has-child", timeout=5.0, system=a) is True
    ask_sync(parent, "stop-child", timeout=5.0, system=a)
    await_condition(
        lambda: ask_sync(parent, "has-child", timeout=5.0, system=a) is False,
        max_time=10.0, message="remote child name never freed")
    # the regression: this second spawn raised InvalidActorNameException
    assert ask_sync(parent, "spawn", timeout=5.0, system=a) == "spawned"


def test_selection_resolves_remote_deployed_child(two_systems):
    """ADVICE r2 (cell.py:111): get_single_child must consult
    _remote_children so a selection to the child's logical /user path
    reaches the remote-deployed actor instead of dead-lettering."""
    a, b = two_systems
    parent = a.actor_of(Props.create(SpawnerParent, addr_of(b)), "sel-parent")
    assert ask_sync(parent, "spawn", timeout=5.0, system=a) == "spawned"
    sel = a.actor_selection("akka://depA/user/sel-parent/rc")
    tag, sysname, _path = ask_sync(sel, "where", timeout=5.0, system=a)
    assert sysname == "depB"


def test_cluster_router_pool_settings_validated():
    """ADVICE r2 (cluster/routing.py:33): reference throws for non-positive
    capacity settings."""
    with pytest.raises(ValueError):
        ClusterRouterPoolSettings(total_instances=0)
    with pytest.raises(ValueError):
        ClusterRouterPoolSettings(total_instances=4, max_instances_per_node=0)
    with pytest.raises(ValueError):
        ClusterRouterGroupSettings(total_instances=0)


def test_cluster_router_pool_spreads_least_loaded(two_node_cluster):
    """ADVICE r2 (cluster/routing.py:200): with total < nodes * per-node max,
    routees must spread one-per-node (selectDeploymentTarget order), not pack
    the lexicographically smallest address."""
    systems, clusters = two_node_cluster
    a, b = systems
    router = a.actor_of(
        Props.create(WhereAmI, "spread").with_router(ClusterRouterPool(
            RoundRobinPool(0),
            ClusterRouterPoolSettings(total_instances=2,
                                      max_instances_per_node=2))),
        "spread-pool")
    await_condition(lambda: _routee_count(a, router) == 2, max_time=10.0,
                    message="pool did not reach 2 routees")
    homes = set()
    for _ in range(6):
        _, sysname, _ = ask_sync(router, "where", timeout=5.0, system=a)
        homes.add(sysname)
    assert homes == {"crt0", "crt1"}, f"routees packed onto {homes}"


def test_cluster_router_removes_downed_node(two_node_cluster):
    systems, clusters = two_node_cluster
    a, b = systems
    router = a.actor_of(
        Props.create(WhereAmI).with_router(ClusterRouterPool(
            RoundRobinPool(0),
            ClusterRouterPoolSettings(total_instances=2,
                                      max_instances_per_node=1))),
        "shrink-pool")
    await_condition(lambda: _routee_count(a, router) == 2, max_time=10.0,
                    message="pool did not fill")
    clusters[0].down(str(b.provider.local_address))
    await_condition(lambda: _routee_count(a, router) == 1, max_time=10.0,
                    message="downed node's routee not removed")
    # survivors all local now
    for _ in range(3):
        _, sysname, _ = ask_sync(router, "where", timeout=5.0, system=a)
        assert sysname == "crt0"
