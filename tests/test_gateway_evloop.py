"""Selector event-loop front door (gateway/evloop.py, ISSUE 18
tentpole a): the evloop transport against its stream A/B twin —
roundtrip, per-connection FIFO on BOTH transports, and the slow-consumer
backpressure twin (a stalled reader stalls only itself).

Tier-1 scope: the roundtrip/equivalence tests ride a fresh region of the
warm "gwb" spec shape (2 shards x 8 entities, 2 devices, payload width
4); everything else is backend-free JSON echo traffic. Windows stay
<= 64 rows."""

from __future__ import annotations

import socket
import threading
import time

import pytest

from akka_tpu import ActorSystem
from akka_tpu.gateway import (AdmissionController, GatewayClient,
                              GatewayServer, RegionBackend, SloTracker,
                              counter_behavior)
from akka_tpu.gateway.ingress import FrameReader, encode_frame


def _fresh_region():
    from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion
    spec = DeviceEntity("gwb", counter_behavior(4), n_shards=2,
                        entities_per_shard=8, n_devices=2, payload_width=4)
    return DeviceShardRegion(spec)


def _mk_system(name):
    return ActorSystem(name, {"akka": {"stdout-loglevel": "OFF",
                                       "log-dead-letters": 0}})


def _echo_server(transport, system=None, **kw):
    """Backend-free server: unknown ops echo typed errors, no region."""
    return GatewayServer(system, None,
                         AdmissionController(rate=1e9, burst=1e9),
                         SloTracker(), transport=transport,
                         aggregate=(transport == "stream"), **kw)


# ---------------------------------------------------------------- roundtrip
def test_evloop_tcp_roundtrip():
    """The stream roundtrip test's evloop twin: same client, same wire
    protocol, region-backed adds/gets plus the admin sum — no actor
    system needed for the transport itself."""
    region = _fresh_region()
    srv = GatewayServer(None, RegionBackend(region),
                        AdmissionController(rate=1e6, burst=1e6),
                        SloTracker(), transport="evloop")
    host, port = srv.start()
    client = GatewayClient(host, port)
    try:
        base = float(client.admin("sum")["value"])
        assert client.request("t9", "ev-acct", "add", 2.5)["status"] == "ok"
        rep = client.request("t9", "ev-acct", "add", 1.5)
        assert rep["status"] == "ok" and rep["value"] == pytest.approx(4.0)
        assert client.request("t9", "ev-acct", "get")["value"] == \
            pytest.approx(4.0)
        assert float(client.admin("sum")["value"]) == \
            pytest.approx(base + 4.0)
    finally:
        client.close()
        srv.stop()


def test_transport_ab_equivalence_one_region():
    """A/B contract: the two transports speak the same wire protocol
    over the same serve path — identical reply dicts for the same
    request schedule (fresh entities per leg so device state aligns),
    and identical admitted/rejected admission counters."""
    region = _fresh_region()
    system = _mk_system("gw-ab-ev")
    schedule = [("add", 2.0), ("add", 3.5), ("get", 0.0),
                ("bogus_op", 1.0)]
    legs = {}
    try:
        for transport, entity in (("stream", "ab-s"), ("evloop", "ab-e")):
            adm = AdmissionController(rate=1e6, burst=1e6)
            srv = GatewayServer(system, RegionBackend(region), adm,
                                SloTracker(), transport=transport)
            host, port = srv.start()
            client = GatewayClient(host, port)
            try:
                reps = [client.request("tA", entity, op, v)
                        for op, v in schedule]
            finally:
                client.close()
                srv.stop()
            for r in reps:
                r.pop("id", None)
            legs[transport] = (reps, adm.stats()["admitted"],
                               adm.stats()["rejected"])
    finally:
        system.terminate()
        system.await_termination(10.0)
    assert legs["stream"] == legs["evloop"]


# ------------------------------------------------------- per-connection FIFO
@pytest.mark.parametrize("transport", ["stream", "evloop"])
def test_per_connection_fifo_both_transports(transport):
    """Acceptance criterion: two connections pipeline interleaved JSON
    frames through the shared aggregator; each gets its replies back in
    exactly its own submit order (the stream leg runs aggregate=True so
    both legs exercise the windowed path)."""
    system = _mk_system(f"gw-fifo-{transport}") \
        if transport == "stream" else None
    srv = _echo_server(transport, system)
    N = 60
    try:
        host, port = srv.start()
        socks = [socket.create_connection((host, port)) for _ in range(2)]
        for s in socks:
            s.settimeout(60.0)
        for j, s in enumerate(socks):
            s.sendall(b"".join(
                encode_frame({"id": i, "tenant": f"t{j}", "entity": "e",
                              "op": "zzz"}) for i in range(N)))
        for s in socks:
            reader, got = FrameReader(), []
            while len(got) < N:
                data = s.recv(65536)
                assert data, "connection died mid-drain"
                got.extend(reader.feed(data))
            assert [g["id"] for g in got] == list(range(N))
            assert all(g["reason"].startswith("unknown_op:") for g in got)
            s.close()
    finally:
        srv.stop()
        if system is not None:
            system.terminate()
            system.await_termination(10.0)


# ------------------------------------------------------------- backpressure
def test_evloop_slow_consumer_backpressure():
    """The stream slow-consumer test's evloop twin: a stalled reader's
    replies pile into ITS outbuf until the high-water mark drops the
    socket's read interest — processing plateaus below the request
    count — while a second live connection keeps being served; the
    stalled one then drains with zero loss and intact ordering."""
    N, OP = 240, "x" * 30000  # unknown op -> ~30KB echo reply
    slo = SloTracker()
    srv = GatewayServer(None, None,
                        AdmissionController(rate=1e9, burst=1e9), slo,
                        max_frame=1 << 16, transport="evloop")
    try:
        host, port = srv.start()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        sock.settimeout(120.0)
        sock.connect((host, port))
        blob = b"".join(
            encode_frame({"id": i, "tenant": "t", "entity": "e", "op": OP})
            for i in range(N))
        sender = threading.Thread(target=sock.sendall, args=(blob,),
                                  daemon=True)
        sender.start()

        def processed():
            return slo.artifact()["requests"]

        last, stable_since = -1, time.monotonic()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            cur = processed()
            if cur != last:
                last, stable_since = cur, time.monotonic()
            elif cur > 0 and time.monotonic() - stable_since > 1.0:
                break  # plateaued: backpressure reached the producer
            time.sleep(0.05)
        plateau = processed()
        assert 0 < plateau < N, \
            f"no backpressure: {plateau}/{N} processed while stalled"
        assert srv._evloop.stats()["read_pauses"] > 0

        # the stall is per-connection: a second socket stays live
        live = GatewayClient(host, port)
        assert live.request("t2", "e", "ping_op", 0.0)["reason"] \
            .startswith("unknown_op:")
        live.close()
        assert processed() == plateau + 1

        # resume: drain everything — no drops, order preserved
        reader = FrameReader(max_frame=1 << 20)
        got = []
        while len(got) < N:
            data = sock.recv(65536)
            assert data, f"connection died after {len(got)}/{N} replies"
            got.extend(reader.feed(data))
        sender.join(timeout=60.0)
        assert not sender.is_alive()
        assert [g["id"] for g in got] == list(range(N))
        assert all(g["status"] == "error" and
                   g["reason"].startswith("unknown_op:") for g in got)
        assert processed() == N + 1
        sock.close()
    finally:
        srv.stop()
