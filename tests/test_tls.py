"""TLS on the host wire + PKI (VERDICT r2 #6).

Reference: akka-remote/src/main/scala/akka/remote/artery/tcp/
SSLEngineProvider.scala:66 (server/client engines, mutual auth),
tcp/ssl/ConfigSSLEngineProvider; akka-pki/.../pem/PEMDecoder.scala:16,
DERPrivateKeyLoader.scala:26."""

import subprocess
import time

import pytest

from akka_tpu import ActorSystem
from akka_tpu.cluster import Cluster, MemberStatus
from akka_tpu.pki import (DERPrivateKeyLoader, PEMLoadingException, decode,
                          decode_all, load_certificates, load_private_key)
from akka_tpu.testkit import await_condition


def _sh(*args):
    subprocess.run(args, check=True, capture_output=True)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """A CA, two CA-signed node certs, and a rogue self-signed cert."""
    d = tmp_path_factory.mktemp("pki")
    _sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(d / "ca.key"), "-out", str(d / "ca.crt"),
        "-days", "1", "-subj", "/CN=test-ca")
    for name in ("node0", "node1"):
        _sh("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(d / f"{name}.key"), "-out", str(d / f"{name}.csr"),
            "-subj", f"/CN={name}")
        _sh("openssl", "x509", "-req", "-in", str(d / f"{name}.csr"),
            "-CA", str(d / "ca.crt"), "-CAkey", str(d / "ca.key"),
            "-CAcreateserial", "-out", str(d / f"{name}.crt"), "-days", "1")
    _sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(d / "rogue.key"), "-out", str(d / "rogue.crt"),
        "-days", "1", "-subj", "/CN=rogue")
    return d


# -- PKI ----------------------------------------------------------------------

def test_pem_decode_and_key_classification(certs):
    blocks = load_certificates(str(certs / "ca.crt"))
    assert blocks[0].label == "CERTIFICATE"
    assert blocks[0].bytes[:1] == b"\x30"  # DER SEQUENCE

    key = load_private_key(str(certs / "node0.key"))
    assert key.format == "PKCS#8"      # openssl genpkey default
    assert key.algorithm == "RSA"


def test_pem_decode_errors():
    with pytest.raises(PEMLoadingException):
        decode("not pem at all")
    with pytest.raises(PEMLoadingException):
        decode("-----BEGIN CERTIFICATE-----\n!!!\n-----END CERTIFICATE-----")
    with pytest.raises(PEMLoadingException):
        decode("-----BEGIN CERTIFICATE-----\nQUJD\n-----END PRIVATE KEY-----")
    with pytest.raises(PEMLoadingException):
        DERPrivateKeyLoader.load(decode(
            "-----BEGIN CERTIFICATE-----\nQUJD\n-----END CERTIFICATE-----"))


def test_oid_decoding_multibyte_first_arc():
    """Regression (r3 review): OIDs under joint-iso-itu-t(2) with arc2 >= 40
    pack the first subidentifier in multiple base-128 bytes; 2.999 is the
    canonical example (encodes as 88 37)."""
    from akka_tpu.pki.pem import _decode_oid
    assert _decode_oid(bytes([0x88, 0x37])) == "2.999"
    assert _decode_oid(bytes([0x2A, 0x86, 0x48, 0x86, 0xF7, 0x0D, 0x01,
                              0x01, 0x01])) == "1.2.840.113549.1.1.1"
    assert _decode_oid(bytes([0x2B, 0x65, 0x70])) == "1.3.101.112"
    with pytest.raises(PEMLoadingException):
        _decode_oid(bytes([0x88]))  # dangling continuation bit
    with pytest.raises(PEMLoadingException):
        _decode_oid(bytes([0x2A, 0x80]))  # zero-payload dangling byte
    with pytest.raises(PEMLoadingException):
        _decode_oid(bytes([0x80]))  # nothing but a continuation byte


def test_pem_decode_multiple_blocks(certs):
    chain = (certs / "node0.crt").read_text() + (certs / "ca.crt").read_text()
    blocks = decode_all(chain)
    assert [b.label for b in blocks] == ["CERTIFICATE", "CERTIFICATE"]


# -- TLS transport ------------------------------------------------------------

def _tls_system(name, port, certs, cert, key, seed_port=None):
    cfg = {"akka": {"actor": {"provider": "cluster"},
                    "stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "remote": {"transport": "tls-tcp",
                               "canonical": {"hostname": "127.0.0.1",
                                             "port": port},
                               "tls": {"cert-file": str(certs / cert),
                                       "key-file": str(certs / key),
                                       "ca-file": str(certs / "ca.crt")}},
                    "cluster": {"gossip-interval": "0.1s",
                                "leader-actions-interval": "0.1s",
                                "failure-detector": {
                                    "heartbeat-interval": "0.2s",
                                    "acceptable-heartbeat-pause": "3s"}}}}
    return ActorSystem.create(name, cfg)


def _up_count(system):
    return sum(1 for m in Cluster.get(system).state.members
               if m.status is MemberStatus.UP)


def test_cluster_forms_over_tls_with_client_certs(certs):
    a = _tls_system("tlsA", 23710, certs, "node0.crt", "node0.key")
    b = _tls_system("tlsB", 23711, certs, "node1.crt", "node1.key")
    try:
        seed = "akka://tlsA@127.0.0.1:23710"
        Cluster.get(a).join(seed)
        Cluster.get(b).join(seed)
        await_condition(lambda: _up_count(a) == 2 and _up_count(b) == 2,
                        max_time=20.0,
                        message="TLS cluster did not form")
    finally:
        for s in (b, a):
            s.terminate()
            s.await_termination(10.0)


def test_bad_cert_is_rejected(certs):
    """Mutual auth: a node presenting a self-signed (non-CA) cert cannot
    join — the handshake fails and the cluster stays at 1 member."""
    a = _tls_system("tlsC", 23712, certs, "node0.crt", "node0.key")
    rogue = _tls_system("tlsR", 23713, certs, "rogue.crt", "rogue.key")
    try:
        seed = "akka://tlsC@127.0.0.1:23712"
        Cluster.get(a).join(seed)
        await_condition(lambda: _up_count(a) == 1, max_time=10.0,
                        message="seed did not self-form")
        Cluster.get(rogue).join(seed)
        time.sleep(3.0)
        assert _up_count(a) == 1, "rogue node must not be admitted"
        assert _up_count(rogue) <= 1
    finally:
        for s in (rogue, a):
            s.terminate()
            s.await_termination(10.0)


def test_tls_misconfiguration_fails_fast(certs, tmp_path):
    bad = tmp_path / "bad.pem"
    bad.write_text("garbage")
    with pytest.raises(Exception):
        cfg = {"akka": {"actor": {"provider": "remote"},
                        "stdout-loglevel": "OFF",
                        "remote": {"transport": "tls-tcp",
                                   "canonical": {"hostname": "127.0.0.1",
                                                 "port": 0},
                                   "tls": {"cert-file": str(bad),
                                           "key-file": str(bad),
                                           "ca-file": str(bad)}}}}
        s = ActorSystem.create("tlsBad", cfg)
        s.terminate()
