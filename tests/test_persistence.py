"""Persistence tests — modeled on the reference's specs
(akka-persistence/src/test/scala: PersistentActorSpec, SnapshotSpec,
AtLeastOnceDeliverySpec, PersistentActorRecoveryTimeoutSpec;
persistence-tck JournalSpec/SnapshotStoreSpec; persistence-query
EventsByPersistenceIdSpec/EventsByTagSpec; typed
EventSourcedBehaviorSpec)."""

import time

import pytest

from akka_tpu import ActorSystem, Props
from akka_tpu.persistence import (AtLeastOnceDelivery, Effect,
                                  EventSourcedBehavior, FailNextN,
                                  FileJournal, InMemJournal,
                                  InMemSnapshotStore, LocalSnapshotStore,
                                  NoOffset, Persistence, PersistenceId,
                                  PersistenceQuery, PersistenceTestKitJournal,
                                  PersistentActor, RecoveryCompleted,
                                  RejectNextN, RetentionCriteria,
                                  SaveSnapshotSuccess, SnapshotOffer, Tagged,
                                  UnconfirmedWarning, journal_tck,
                                  slab_snapshot, snapshot_store_tck)
from akka_tpu.testkit import TestProbe, await_condition

CFG = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                "persistence": {"snapshot-store": {
                    "plugin": "akka.persistence.snapshot-store.inmem"}}}}

_sys_counter = [0]


@pytest.fixture()
def system():
    _sys_counter[0] += 1
    s = ActorSystem.create(f"persist-test-{_sys_counter[0]}", CFG)
    yield s
    s.terminate()
    s.await_termination(10.0)


# -- TCK: every plugin implementation passes the same compliance suite -------

def test_journal_tck_inmem():
    journal_tck(InMemJournal)


def test_journal_tck_file(tmp_path):
    counter = [0]

    def fresh():
        counter[0] += 1
        return FileJournal(str(tmp_path / f"j{counter[0]}"))
    journal_tck(fresh)


def test_journal_tck_testkit_journal():
    journal_tck(PersistenceTestKitJournal)


def test_snapshot_tck_inmem():
    snapshot_store_tck(InMemSnapshotStore)


def test_snapshot_tck_local(tmp_path):
    counter = [0]

    def fresh():
        counter[0] += 1
        return LocalSnapshotStore(str(tmp_path / f"s{counter[0]}"))
    snapshot_store_tck(fresh)


def test_file_journal_survives_reopen(tmp_path):
    from akka_tpu.persistence import AtomicWrite, PersistentRepr
    d = str(tmp_path / "jj")
    j = FileJournal(d)
    j.write_atomic(AtomicWrite((PersistentRepr("a", 1, "p"),
                                PersistentRepr("b", 2, "p"))))
    j2 = FileJournal(d)  # fresh process equivalent
    got = []
    j2.replay("p", 1, 2**63 - 1, 2**63 - 1, got.append)
    assert [r.payload for r in got] == ["a", "b"]
    assert j2.highest_sequence_nr("p", 0) == 2
    assert j2.persistence_ids() == ["p"]


# -- classic PersistentActor --------------------------------------------------

class Counter(PersistentActor):
    def __init__(self, pid: str, probe=None):
        super().__init__()
        self._pid = pid
        self.count = 0
        self.probe = probe

    @property
    def persistence_id(self) -> str:
        return self._pid

    def receive_recover(self, message):
        if isinstance(message, SnapshotOffer):
            self.count = message.snapshot
        elif isinstance(message, RecoveryCompleted):
            if self.probe:
                self.probe.tell(("recovered", self.count), self.self_ref)
        elif isinstance(message, int):
            self.count += message
        else:
            return NotImplemented

    def receive_command(self, message):
        if message == "get":
            self.sender.tell(self.count, self.self_ref)
        elif isinstance(message, int):
            def handler(ev):
                self.count += ev
                if self.probe:
                    self.probe.tell(("persisted", ev, self.count), self.self_ref)
            self.persist(message, handler)
        elif message == "snap":
            self.save_snapshot(self.count)
        elif isinstance(message, SaveSnapshotSuccess):
            if self.probe:
                self.probe.tell(("snapped", message.metadata.sequence_nr),
                                self.self_ref)
        else:
            return NotImplemented


def test_persist_and_recover(system):
    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Counter, "c1", probe.ref), "c1")
    assert probe.receive_one(5.0) == ("recovered", 0)
    for i in (1, 2, 3):
        ref.tell(i, probe.ref)
    assert probe.receive_one(5.0) == ("persisted", 1, 1)
    assert probe.receive_one(5.0) == ("persisted", 2, 3)
    assert probe.receive_one(5.0) == ("persisted", 3, 6)

    # restart: a fresh incarnation replays the journal
    system.stop(ref)
    probe.watch(ref)
    probe.expect_terminated(ref, 5.0)
    ref2 = system.actor_of(Props.create(Counter, "c1", probe.ref), "c1b")
    assert probe.receive_one(5.0) == ("recovered", 6)
    ref2.tell("get", probe.ref)
    assert probe.receive_one(5.0) == 6


def test_stash_while_persisting_preserves_order(system):
    """Commands sent while a persist is in flight are processed after the
    handler (reference Eventsourced stash :218-233)."""
    order = []

    class Tracker(PersistentActor):
        @property
        def persistence_id(self):
            return "tracker"

        def receive_recover(self, message):
            pass

        def receive_command(self, message):
            if message == "a":
                order.append("cmd-a")
                self.persist("ev-a", lambda ev: order.append("handler-a"))
            else:
                order.append(f"cmd-{message}")
                self.sender.tell("done", self.self_ref)

    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Tracker))
    ref.tell("a", probe.ref)
    ref.tell("b", probe.ref)  # arrives while ev-a write is in flight
    probe.expect_msg("done", 5.0)
    assert order == ["cmd-a", "handler-a", "cmd-b"]


def test_snapshot_speeds_recovery(system):
    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Counter, "c2", probe.ref))
    probe.receive_one(5.0)  # recovered
    for i in range(5):
        ref.tell(1, probe.ref)
        probe.receive_one(5.0)
    ref.tell("snap", probe.ref)
    assert probe.receive_one(5.0)[0] == "snapped"
    ref.tell(1, probe.ref)   # one event after the snapshot
    probe.receive_one(5.0)

    ref2 = system.actor_of(Props.create(Counter, "c2", probe.ref))
    assert probe.receive_one(5.0) == ("recovered", 6)


def test_persist_failure_stops_actor(system):
    Persistence.register_journal_plugin(
        "test.failing-journal", lambda sys_, cfg: failing_journal)
    failing_journal = PersistenceTestKitJournal()

    class Failing(Counter):
        journal_plugin_id = "test.failing-journal"

    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Failing, "f1", probe.ref))
    assert probe.receive_one(5.0) == ("recovered", 0)
    probe.watch(ref)
    failing_journal.set_policy(FailNextN(1))
    ref.tell(1, probe.ref)
    probe.expect_terminated(ref, 5.0)


def test_persist_rejection_keeps_actor_running(system):
    rejecting = PersistenceTestKitJournal()
    Persistence.register_journal_plugin(
        "test.rejecting-journal", lambda sys_, cfg: rejecting)

    class Rejecting(Counter):
        journal_plugin_id = "test.rejecting-journal"

    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Rejecting, "r1", probe.ref))
    assert probe.receive_one(5.0) == ("recovered", 0)
    rejecting.set_policy(RejectNextN(1))
    ref.tell(1, probe.ref)     # rejected: no handler call, no state change
    ref.tell(2, probe.ref)     # accepted
    assert probe.receive_one(5.0) == ("persisted", 2, 2)
    ref.tell("get", probe.ref)
    assert probe.receive_one(5.0) == 2


# -- at-least-once delivery ---------------------------------------------------

def test_at_least_once_delivery_redelivers_until_confirm(system):
    class Sender(AtLeastOnceDelivery):
        redeliver_interval = 0.2

        def __init__(self, dest):
            super().__init__()
            self.dest = dest

        @property
        def persistence_id(self):
            return "alod-sender"

        def receive_recover(self, message):
            pass

        def receive_command(self, message):
            if message == "send":
                self.persist("msg-sent", lambda ev: self.deliver(
                    self.dest, lambda did: ("payload", did)))
            elif isinstance(message, tuple) and message[0] == "confirm":
                self.persist(("confirmed", message[1]),
                             lambda ev: self.confirm_delivery(ev[1]))
            elif message == "unconfirmed?":
                self.sender.tell(self.number_of_unconfirmed, self.self_ref)

    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Sender, probe.ref))
    ref.tell("send", probe.ref)
    first = probe.receive_one(5.0)
    assert first[0] == "payload"
    did = first[1]
    # not confirmed -> redelivered
    second = probe.receive_one(5.0)
    assert second == first
    ref.tell(("confirm", did), probe.ref)
    await_condition(lambda: _unconfirmed(ref, system) == 0, max_time=5.0)
    probe_quiet = TestProbe(system)
    time.sleep(0.5)  # no more redeliveries after confirm
    assert probe.ref is not None


def _unconfirmed(ref, system):
    from akka_tpu.pattern.ask import ask_sync
    try:
        return ask_sync(ref, "unconfirmed?", timeout=2.0)
    except Exception:  # noqa: BLE001
        return -1


# -- typed EventSourcedBehavior ----------------------------------------------

def test_typed_event_sourced_counter(system):
    probe = TestProbe(system)

    def command_handler(state, cmd):
        if cmd[0] == "add":
            return Effect.persist(("added", cmd[1])).then_reply(
                cmd[2], lambda s: ("total", s))
        if cmd[0] == "get":
            return Effect.reply(cmd[1], ("total", state))
        return Effect.unhandled()

    def event_handler(state, event):
        if event[0] == "added":
            return state + event[1]
        return state

    def make():
        return EventSourcedBehavior(
            PersistenceId.of("Counter", "t1"), 0, command_handler,
            event_handler, retention=RetentionCriteria.snapshot_every_n(100))

    from akka_tpu.typed.adapter import props_from_behavior
    ref = system.actor_of(props_from_behavior(make()), "typed-counter")
    ref.tell(("add", 5, probe.ref))
    assert probe.receive_one(5.0) == ("total", 5)
    ref.tell(("add", 7, probe.ref))
    assert probe.receive_one(5.0) == ("total", 12)

    # recovery in a fresh incarnation
    ref2 = system.actor_of(props_from_behavior(make()), "typed-counter2")
    ref2.tell(("get", probe.ref))
    assert probe.receive_one(5.0) == ("total", 12)


def test_typed_effect_stop_and_none(system):
    probe = TestProbe(system)

    def command_handler(state, cmd):
        if cmd == "stop":
            return Effect.stop()
        if cmd == "noop":
            return Effect.none().then_run(
                lambda s: probe.ref.tell(("ran", s), None))
        return Effect.unhandled()

    from akka_tpu.typed.adapter import props_from_behavior
    beh = EventSourcedBehavior(PersistenceId.of_unique_id("stopper"), 0,
                               command_handler, lambda s, e: s)
    ref = system.actor_of(props_from_behavior(beh))
    ref.tell("noop")
    assert probe.receive_one(5.0) == ("ran", 0)
    probe.watch(ref)
    ref.tell("stop")
    probe.expect_terminated(ref, 5.0)


def test_typed_supervised_restart_rereplays_journal(system):
    """A supervised restart must re-run recovery from the journal, not reuse
    the crashed incarnation's in-memory state (Running.scala restart)."""
    from akka_tpu.typed import Behaviors, SupervisorStrategy
    from akka_tpu.typed.adapter import props_from_behavior
    probe = TestProbe(system)

    def ch(state, cmd):
        if cmd[0] == "add":
            return Effect.persist(("added", cmd[1])).then_reply(
                cmd[2], lambda s: ("total", s))
        if cmd[0] == "boom":
            raise RuntimeError("kaboom")
        if cmd[0] == "get":
            return Effect.reply(cmd[1], ("total", state))
        return Effect.unhandled()

    beh = EventSourcedBehavior(PersistenceId.of("Sup", "s1"), 0, ch,
                               lambda s, e: s + e[1])
    sup = Behaviors.supervise(beh).on_failure(
        SupervisorStrategy.restart(), RuntimeError)
    ref = system.actor_of(props_from_behavior(sup), "sup-es")
    ref.tell(("add", 3, probe.ref))
    assert probe.receive_one(5.0) == ("total", 3)
    ref.tell(("boom",))
    # post-restart state comes from journal replay, not the crashed instance
    ref.tell(("get", probe.ref))
    assert probe.receive_one(5.0) == ("total", 3)
    ref.tell(("add", 4, probe.ref))
    assert probe.receive_one(5.0) == ("total", 7)


def test_file_journal_atomic_rejection(tmp_path):
    """An unserializable event in an AtomicWrite must reject the WHOLE batch
    with nothing written (all-or-nothing contract)."""
    from akka_tpu.persistence import AtomicWrite, PersistentRepr
    j = FileJournal(str(tmp_path / "aj"))
    bad = AtomicWrite((PersistentRepr("fine", 1, "p"),
                       PersistentRepr(lambda: None, 2, "p")))  # unpicklable
    assert j.write_atomic(bad) is not None  # rejected
    got = []
    j.replay("p", 1, 2**63 - 1, 2**63 - 1, got.append)
    assert got == [], "rejected batch must leave no events behind"
    assert j.highest_sequence_nr("p", 0) == 0


# -- persistence query --------------------------------------------------------

def test_query_current_and_live(system):
    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Counter, "q1", probe.ref))
    probe.receive_one(5.0)
    for i in (1, 2):
        ref.tell(i, probe.ref)
        probe.receive_one(5.0)

    rj = PersistenceQuery.get(system).read_journal_for()
    assert "q1" in rj.current_persistence_ids()
    envs = rj.current_events_by_persistence_id("q1")
    assert [e.event for e in envs] == [1, 2]
    assert [e.sequence_nr for e in envs] == [1, 2]

    live = rj.events_by_persistence_id("q1")
    got = live.drain()
    assert [e.event for e in got] == [1, 2]
    ref.tell(9, probe.ref)
    probe.receive_one(5.0)
    nxt = live.poll(5.0)
    assert nxt is not None and nxt.event == 9
    live.close()


def test_query_events_by_tag(system):
    class Tagger(PersistentActor):
        @property
        def persistence_id(self):
            return "tagger-1"

        def receive_recover(self, message):
            pass

        def receive_command(self, message):
            self.persist(Tagged.of(message, "blue"),
                         lambda ev: self.sender.tell("ok", self.self_ref))

    probe = TestProbe(system)
    ref = system.actor_of(Props.create(Tagger))
    ref.tell("e1", probe.ref)
    probe.expect_msg("ok", 5.0)
    ref.tell("e2", probe.ref)
    probe.expect_msg("ok", 5.0)

    rj = PersistenceQuery.get(system).read_journal_for()
    envs = rj.current_events_by_tag("blue", NoOffset)
    assert [e.event for e in envs] == ["e1", "e2"]
    # replay of the actor sees UNtagged payloads
    replayed = rj.current_events_by_persistence_id("tagger-1")
    assert [e.event for e in replayed] == ["e1", "e2"]


# -- TPU slab snapshots -------------------------------------------------------

def test_slab_snapshot_roundtrip(tmp_path):
    from akka_tpu.models.baseline_benches import build_ring, seed_ring_full

    sys_ = build_ring(64)
    seed_ring_full(sys_)
    sys_.run(3)
    sys_.block_until_ready()
    path = slab_snapshot.save_slabs(sys_, str(tmp_path))

    sys2 = build_ring(64)
    slab_snapshot.restore_slabs(sys2, path)
    import numpy as np
    assert (np.asarray(sys2.read_state("received")) ==
            np.asarray(sys_.read_state("received"))).all()
    # restored system continues stepping identically
    sys_.run(2); sys_.block_until_ready()
    sys2.run(2); sys2.block_until_ready()
    assert (np.asarray(sys2.read_state("received")) ==
            np.asarray(sys_.read_state("received"))).all()
    assert slab_snapshot.latest_slab_path(str(tmp_path)) == path
