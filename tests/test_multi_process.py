"""REAL multi-process cluster tests: separate OS processes, real TCP
transport, barriers via the conductor (VERDICT r1 item 5; reference:
MultiNodeSpec.scala:258 roles/barriers, Conductor.scala:56; membership
semantics ClusterDaemon.scala:312).

Each worker is a fresh python process with a sanitized CPU-jax env; the
cluster forms over actual sockets with the fixed-schema wire codec
(pickle disabled), gossips to convergence, and survives a blackholed
partition via split-brain resolution."""

import pytest

from akka_tpu.testkit.multi_process import spawn_nodes

pytestmark = pytest.mark.slow


_COMMON = r"""
import json, os, sys, time
from akka_tpu import ActorSystem
from akka_tpu.cluster import Cluster
from akka_tpu.testkit.dilation import dilated, dilated_s
from akka_tpu.testkit.multi_process import (node_barrier, node_index,
                                            node_count, node_result)

IDX = node_index()
N = node_count()
BASE_PORT = int(os.environ["AKKA_TPU_TEST_BASE_PORT"])

def make_system(extra=None):
    # starvation windows (heartbeat pause, SBR timing overridden by tests)
    # auto-dilate with machine load (TestKit.scala:244-319 `dilated`
    # discipline): N extra busy processes must widen deadlines, not flake
    cfg = {"akka": {"actor": {"provider": "cluster"},
                    "stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "remote": {"transport": "tcp",
                               "canonical": {"hostname": "127.0.0.1",
                                             "port": BASE_PORT + IDX}},
                    "cluster": {"gossip-interval": "0.1s",
                                "leader-actions-interval": "0.1s",
                                "unreachable-nodes-reaper-interval": "0.2s",
                                "failure-detector": {
                                    "heartbeat-interval": "0.2s",
                                    "acceptable-heartbeat-pause":
                                        dilated_s(2.0)}}}}
    if extra:
        def deep(dst, src):
            for k, v in src.items():
                if isinstance(v, dict):
                    deep(dst.setdefault(k, {}), v)
                else:
                    dst[k] = v
        deep(cfg, extra)
    return ActorSystem(f"mp{IDX}", cfg)

def up_count(system):
    return len([m for m in Cluster.get(system).state.members
                if m.status.value == "Up"])

def await_(cond, secs, what):
    deadline = time.monotonic() + dilated(secs)
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError("timeout waiting for " + what)
"""


def test_two_process_cluster_forms_and_converges():
    worker = _COMMON + r"""
system = make_system()
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot")
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 2, 30, "2 members Up")
node_barrier("converged")
state = Cluster.get(system).state
node_result({"up": up_count(system),
             "leader": str(state.leader) if state.leader else None})
node_barrier("done")
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 2, timeout=120.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23510"})
    assert results[0]["up"] == 2 and results[1]["up"] == 2
    # both sides agree on the leader (gossip convergence)
    assert results[0]["leader"] == results[1]["leader"] is not None


def test_three_process_partition_sbr_downs_minority():
    worker = _COMMON + r"""
system = make_system({"akka": {"cluster": {
    "split-brain-resolver": {"active-strategy": "keep-majority",
                             "stable-after": dilated_s(1.0)},
    "down-removal-margin": dilated_s(0.5)}}})
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot")
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 3, 40, "3 members Up")
node_barrier("converged")

# partition: node 2 blackholed from {0, 1} in BOTH directions
tr = system.provider.transport
me = f"127.0.0.1:{BASE_PORT + IDX}"
if IDX == 2:
    for other in (0, 1):
        tr.fault_injector.blackhole(me, f"127.0.0.1:{BASE_PORT + other}")
else:
    tr.fault_injector.blackhole(me, f"127.0.0.1:{BASE_PORT + 2}")
node_barrier("partitioned")

if IDX in (0, 1):
    # majority side: SBR downs the unreachable minority; cluster heals to 2
    await_(lambda: up_count(system) == 2 and len(
        Cluster.get(system).state.members) == 2, 60, "minority removed")
    node_result({"up": up_count(system), "side": "majority"})
else:
    # minority side: downs ITSELF (keep-majority on the losing side)
    c = Cluster.get(system)
    assert c.await_removed(60.0), "minority never downed itself"
    node_result({"side": "minority", "downed": True})
node_barrier("checked")
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 3, timeout=180.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23520"})
    assert results[0]["up"] == 2 and results[1]["up"] == 2
    assert results[2]["downed"] is True


def test_three_process_partition_resolved_by_lease(tmp_path):
    """VERDICT r2 #7 done-criterion: a partitioned 3-process cluster
    resolves via the LEASE (file-backed — a real cross-process lock): the
    side that acquires it survives, the other downs itself."""
    worker = _COMMON + r"""
from akka_tpu.cluster_tools.lease import FileLease
FileLease.directory = os.environ["AKKA_TPU_TEST_LEASE_DIR"]
system = make_system({"akka": {"cluster": {
    "split-brain-resolver": {
        "active-strategy": "lease-majority",
        "stable-after": dilated_s(1.0),
        "lease-majority": {"lease-name": "mp-sbr",
                           "lease-implementation": "file",
                           "heartbeat-interval": "0.3s",
                           "heartbeat-timeout": dilated_s(3.0),
                           "acquire-lease-delay-for-minority":
                               dilated(2.0)}},
    "down-removal-margin": dilated_s(0.5)}}})
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot")
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 3, 40, "3 members Up")
node_barrier("converged")

tr = system.provider.transport
me = f"127.0.0.1:{BASE_PORT + IDX}"
if IDX == 2:
    for other in (0, 1):
        tr.fault_injector.blackhole(me, f"127.0.0.1:{BASE_PORT + other}")
else:
    tr.fault_injector.blackhole(me, f"127.0.0.1:{BASE_PORT + 2}")
node_barrier("partitioned")

if IDX in (0, 1):
    # this side's decider (node 0, lowest address) wins the lease race
    # (2-to-1 timing is not what decides it — the LEASE is)
    await_(lambda: up_count(system) == 2 and len(
        Cluster.get(system).state.members) == 2, 60, "minority removed")
    node_result({"up": up_count(system), "side": "lease-winner"})
else:
    c = Cluster.get(system)
    assert c.await_removed(60.0), "lease loser never downed itself"
    node_result({"side": "lease-loser", "downed": True})
node_barrier("checked")
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(
        worker, 3, timeout=180.0,
        extra_env={"AKKA_TPU_TEST_BASE_PORT": "23550",
                   "AKKA_TPU_TEST_LEASE_DIR": str(tmp_path)})
    assert results[0]["up"] == 2 and results[1]["up"] == 2
    assert results[2]["downed"] is True


def test_tls_cluster_across_real_processes(tmp_path):
    """VERDICT r2 #6 done-criterion: a REAL-process cluster forms over TLS
    with mutual client certs, and a third process presenting a self-signed
    cert is rejected at the handshake (never admitted)."""
    import subprocess

    d = tmp_path

    def sh(*args):
        subprocess.run(args, check=True, capture_output=True)

    sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
       "-keyout", str(d / "ca.key"), "-out", str(d / "ca.crt"),
       "-days", "1", "-subj", "/CN=mp-ca")
    for i in range(2):
        sh("openssl", "req", "-newkey", "rsa:2048", "-nodes",
           "-keyout", str(d / f"node{i}.key"),
           "-out", str(d / f"node{i}.csr"), "-subj", f"/CN=node{i}")
        sh("openssl", "x509", "-req", "-in", str(d / f"node{i}.csr"),
           "-CA", str(d / "ca.crt"), "-CAkey", str(d / "ca.key"),
           "-CAcreateserial", "-out", str(d / f"node{i}.crt"), "-days", "1")
    sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
       "-keyout", str(d / "node2.key"), "-out", str(d / "node2.crt"),
       "-days", "1", "-subj", "/CN=rogue")  # node 2: self-signed

    worker = _COMMON + r"""
CERTS = os.environ["AKKA_TPU_TEST_CERT_DIR"]
system = make_system({"akka": {"remote": {
    "transport": "tls-tcp",
    "tls": {"cert-file": f"{CERTS}/node{IDX}.crt",
            "key-file": f"{CERTS}/node{IDX}.key",
            "ca-file": f"{CERTS}/ca.crt"}}}})
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot")
Cluster.get(system).join(seed)
if IDX < 2:
    await_(lambda: up_count(system) == 2, 40, "2 TLS members Up")
    time.sleep(2.0)  # rogue must STAY out
    node_result({"up": up_count(system)})
else:
    time.sleep(6.0)  # rogue: join handshakes fail silently
    node_result({"up": up_count(system)})
node_barrier("done")
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(
        worker, 3, timeout=180.0,
        extra_env={"AKKA_TPU_TEST_BASE_PORT": "23540",
                   "AKKA_TPU_TEST_CERT_DIR": str(d)})
    assert results[0]["up"] == 2 and results[1]["up"] == 2
    assert results[2]["up"] <= 1  # never admitted


def test_sharded_daemon_process_rehomes_across_real_processes():
    """ShardedDaemonProcess through the REAL multi-process harness
    (VERDICT r4 #7 done-criterion): N always-alive workers spread over two
    OS processes; when the second process dies mid-run, the keep-alive
    pinger revives every index on the survivor — singleton-per-index
    throughout (reference: ShardedDaemonProcessImpl keep-alive +
    one-shard-per-instance design)."""
    worker = _COMMON + r"""
from akka_tpu.sharding import (ShardedDaemonProcess,
                               ShardedDaemonProcessSettings)
from akka_tpu.typed import Behaviors
from akka_tpu.testkit import TestProbe

system = make_system({"akka": {"cluster": {
    "split-brain-resolver": {"active-strategy": "keep-majority",
                             "stable-after": dilated_s(1.0)},
    "down-removal-margin": dilated_s(0.5)}}})
seed = f"akka://mp0@127.0.0.1:{BASE_PORT}"
node_barrier("boot")
Cluster.get(system).join(seed)
await_(lambda: up_count(system) == 2, 40, "2 members Up")
node_barrier("converged")

NWORK = 4
def factory(i):
    return Behaviors.setup(lambda ctx: Behaviors.receive(
        lambda c, m: Behaviors.same()))

region = ShardedDaemonProcess.get(system).init(
    "mp-daemons", NWORK, factory,
    settings=ShardedDaemonProcessSettings(keep_alive_interval=0.3))
probe = TestProbe(system)
from akka_tpu.testkit import region_entity_ids

def local_ids():
    return region_entity_ids(region, probe)

all_ids = {str(i) for i in range(NWORK)}
# report the value an await_ CONFIRMED, never a fresh one-shot re-query
# (a single GetShardRegionState may legitimately return a partial
# snapshot at the region's own aggregation timeout)
confirmed = {}
if IDX == 0:
    # wait until the workers are spread: node 1 hosts at least one
    def spread():
        mine = local_ids()
        return mine and mine != all_ids
    await_(spread, 40, "workers never spread to the second node")
    node_barrier("spread")
    # no further barriers: node 1 dies abruptly after this point and a
    # barrier would wait for it forever. Every index must rehome here.
    def rehomed():
        mine = local_ids()
        if mine == all_ids:
            confirmed["ids"] = mine
            return True
        return False
    await_(rehomed, 60, "workers did not rehome to the survivor")
    await_(lambda: up_count(system) == 1, 60, "dead node never removed")
    node_result({"side": "survivor", "ids": sorted(confirmed["ids"])})
    system.terminate(); system.await_termination(10)
else:
    def hosted_some():
        mine = local_ids()
        if mine:
            confirmed["hosted"] = mine
            return True
        return False
    await_(hosted_some, 40, "no workers ever landed here")
    node_barrier("spread")
    node_result({"side": "leaver", "hosted": sorted(confirmed["hosted"])})
    # die ABRUPTLY (no graceful leave): the cluster must down us and the
    # daemons must rehome via the keep-alive pinger
    os._exit(0)
"""
    results, _ = spawn_nodes(worker, 2, timeout=240.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23560"})
    assert results[0]["side"] == "survivor"
    assert results[0]["ids"] == ["0", "1", "2", "3"]
    assert results[1]["hosted"]  # the leaver really hosted workers first


def test_remote_tell_across_real_processes():
    worker = _COMMON + r"""
from akka_tpu import Actor, Props
from akka_tpu.pattern.ask import ask_sync

system = make_system()

class Echo(Actor):
    def receive(self, msg):
        self.sender.tell(("echo-from", IDX, msg), self.self_ref)

system.actor_of(Props.create(Echo), "echo")
node_barrier("ready")
peer = (IDX + 1) % N
ref = system.actor_selection(
    f"akka://mp{peer}@127.0.0.1:{BASE_PORT + peer}/user/echo")
got = ask_sync(ref, ["hi", IDX], timeout=15.0)
assert got == ("echo-from", peer, ["hi", IDX]), got
node_result({"ok": True})
node_barrier("done")
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 2, timeout=120.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23530"})
    assert results[0]["ok"] and results[1]["ok"]
