"""RememberEntitiesStore SPI conformance (sharding/region.py, ISSUE 15):
one shared contract suite run against all three implementations —
InProc (tests), Journal (record-log file), DData (replicated ORSet of
ids riding the op-delta algebra) — plus the durable-store region seam:
a fresh DeviceShardRegion incarnation respawns every remembered entity
from either durable store with zero client traffic.

Tier-1 budget: the conformance suite is host-only (the ddata leg boots
one single-node in-proc cluster system per test, ~100ms); the respawn
tests ride the test_ask_batch spec shape (2 shards x 16 eps, one
virtual device) so the jit cache is warm.
"""

from __future__ import annotations

import threading

import pytest

from akka_tpu import ActorSystem
from akka_tpu.gateway import counter_behavior
from akka_tpu.sharding import (ClusterShardingSettings,
                               DDataRememberEntitiesStore,
                               InProcRememberEntitiesStore,
                               JournalRememberEntitiesStore,
                               make_remember_entities_store)
from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion

FAST = {"akka": {"actor": {"provider": "cluster"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}}}}

KINDS = ("inproc", "journal", "ddata")


@pytest.fixture(scope="module")
def ddata_system():
    """ONE single-node cluster system for every ddata leg here: system
    teardown costs ~5s, so per-test systems would quadruple this
    module's tier-1 bill for no isolation gain (each test uses fresh
    (type, shard) keys or fresh ids)."""
    from akka_tpu.cluster import Cluster
    from akka_tpu.testkit import await_condition
    system = ActorSystem.create("re-store", FAST)
    c = Cluster.get(system)
    c.join(str(system.provider.local_address))
    await_condition(
        lambda: any(m.status.value == "Up" for m in c.state.members),
        max_time=10.0)
    yield system
    system.terminate()
    system.await_termination(10.0)


@pytest.fixture(params=KINDS)
def store_pair(request, tmp_path):
    """(store, fresh_handle_factory): the factory opens a SECOND handle
    on the same durable substrate — the 'restarted region' view. Each
    ddata leg namespaces its keys by test name, so the shared system
    never leaks state between tests."""
    kind = request.param
    if kind == "inproc":
        InProcRememberEntitiesStore.reset()
        yield InProcRememberEntitiesStore(), InProcRememberEntitiesStore
        InProcRememberEntitiesStore.reset()
        return
    if kind == "journal":
        path = str(tmp_path / "remember.journal")
        store = JournalRememberEntitiesStore(path)
        yield store, lambda: JournalRememberEntitiesStore(path)
        store.close()
        return
    system = request.getfixturevalue("ddata_system")
    prefix = f"re-{request.node.name}"
    yield (DDataRememberEntitiesStore(system, key_prefix=prefix),
           lambda: DDataRememberEntitiesStore(system, key_prefix=prefix))


# ------------------------------------------------------------- conformance
def test_store_add_remove_get(store_pair):
    store, _fresh = store_pair
    assert store.remembered("Counter", "0") == set()
    store.add("Counter", "0", "a")
    store.add("Counter", "0", "b")
    store.add("Counter", "1", "c")
    store.add("Other", "0", "d")  # namespaced by (type, shard)
    store.remove("Counter", "0", "b")
    assert store.remembered("Counter", "0") == {"a"}
    assert store.remembered("Counter", "1") == {"c"}
    assert store.remembered("Other", "0") == {"d"}


def test_store_idempotent_ops(store_pair):
    store, _fresh = store_pair
    for _ in range(3):
        store.add("Counter", "0", "a")  # re-add: no-op, no duplicate
    store.remove("Counter", "0", "missing")  # remove absent: no-op
    store.remove("Counter", "0", "a")
    store.remove("Counter", "0", "a")  # re-remove: no-op
    assert store.remembered("Counter", "0") == set()


def test_store_fresh_handle_sees_prior_adds(store_pair):
    """The restart seam: a second handle on the same substrate reads
    exactly what the first one flushed."""
    store, fresh = store_pair
    store.add("Counter", "0", "x")
    store.add("Counter", "0", "y")
    store.remove("Counter", "0", "y")
    twin = fresh()
    try:
        assert twin.remembered("Counter", "0") == {"x"}
    finally:
        if isinstance(twin, JournalRememberEntitiesStore):
            twin.close()


def test_store_concurrent_region_start(store_pair):
    """Two regions starting concurrently against one store (the
    multi-node boot race): adds from both threads all land."""
    store, _fresh = store_pair
    errors = []

    def boot(node: int) -> None:
        try:
            for i in range(16):
                store.add("Counter", str(i % 2), f"n{node}-e{i}")
                store.add("Counter", "0", "shared")  # contended id
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    ts = [threading.Thread(target=boot, args=(n,)) for n in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    got = store.remembered("Counter", "0") | store.remembered("Counter", "1")
    assert got == ({f"n{n}-e{i}" for n in (0, 1) for i in range(16)}
                   | {"shared"})


def test_journal_store_compact_and_torn_tail(tmp_path):
    path = str(tmp_path / "remember.journal")
    store = JournalRememberEntitiesStore(path)
    for i in range(8):
        store.add("Counter", "0", f"e{i}")
    store.remove("Counter", "0", "e0")
    assert store.compact() == 7
    store.close()
    with open(path, "ab") as f:  # crash-torn trailing record
        f.write((1 << 20).to_bytes(8, "little") + b"torn")
    twin = JournalRememberEntitiesStore(path)
    assert twin.truncated_bytes > 0
    assert twin.remembered("Counter", "0") == {f"e{i}" for i in range(1, 8)}
    twin.close()


def test_settings_factory_resolution(tmp_path):
    assert make_remember_entities_store(ClusterShardingSettings()) is None
    st = make_remember_entities_store(ClusterShardingSettings(
        remember_entities=True))
    assert isinstance(st, InProcRememberEntitiesStore)
    st = make_remember_entities_store(ClusterShardingSettings(
        remember_entities=True, remember_entities_store="journal",
        remember_entities_dir=str(tmp_path)))
    assert isinstance(st, JournalRememberEntitiesStore)
    st.close()
    with pytest.raises(ValueError):
        make_remember_entities_store(ClusterShardingSettings(
            remember_entities=True, remember_entities_store="journal"))
    with pytest.raises(ValueError):
        make_remember_entities_store(ClusterShardingSettings(
            remember_entities=True, remember_entities_store="ddata"))
    with pytest.raises(ValueError):
        make_remember_entities_store(ClusterShardingSettings(
            remember_entities=True, remember_entities_store="nope"))


# ------------------------------------------------------- region respawn
_SPEC_KW = dict(n_shards=2, entities_per_shard=16, n_devices=1,
                payload_width=4)


def _respawn_roundtrip(store_a, fresh_store, type_name):
    """First incarnation registers entities through spec.remember_store;
    a fresh incarnation on a fresh handle (opened AFTER the adds, like a
    restarted process) respawns them all with zero traffic — the
    remember-entities contract at the device layer."""
    spec = DeviceEntity(type_name, counter_behavior(4), **_SPEC_KW,
                        remember_store=store_a)
    r1 = DeviceShardRegion(spec)
    ids = {f"re-{type_name}-{i}" for i in range(6)}
    # sorted registration mirrors _respawn_remembered's sorted order, so
    # placement determinism is assertable (restore() itself pins rows via
    # the sidecar; a store-only respawn is deterministic given the order)
    rows = {e: r1.entity_ref(e).row for e in sorted(ids)}

    store_b = fresh_store()
    spec2 = DeviceEntity(type_name, counter_behavior(4), **_SPEC_KW,
                         remember_store=store_b)
    r2 = DeviceShardRegion(spec2)
    r2._respawn_remembered()
    got = set()
    for shard in range(spec2.n_shards):
        got.update(r2._entities[shard])
    assert got == ids
    # identical spec + sorted respawn: same shard/slot placement, so the
    # replayed totals scatter targets the rows the entities had
    assert {e: r2.entity_ref(e).row for e in ids} == rows
    assert r2.stats()["entities"] >= len(ids)
    return store_b


def test_respawn_remembered_from_journal_store(tmp_path):
    path = str(tmp_path / "remember.journal")
    a = JournalRememberEntitiesStore(path)
    b = None
    try:
        b = _respawn_roundtrip(
            a, lambda: JournalRememberEntitiesStore(path), "re-journal")
    finally:
        a.close()
        if b is not None:
            b.close()


def test_respawn_remembered_from_ddata_store(ddata_system):
    _respawn_roundtrip(DDataRememberEntitiesStore(ddata_system),
                       lambda: DDataRememberEntitiesStore(ddata_system),
                       "re-ddata")
