"""Shard-failure detection and degraded-mesh failover (ISSUE 5).

The acceptance bar mirrors ISSUE 4's honesty standard: a shard killed by
the murmur3 chaos schedule (testkit/chaos.DeviceLossInjector — it freezes
the HOST-OBSERVED attention row, which is exactly the signature a real
preemption leaves) must be detected, evicted, and failed-over by the
MeshSentinel with NO manual restore call, and the run must end
BIT-IDENTICAL to an uninterrupted twin and a numpy oracle on both delivery
backends. Detection runs on an injected manual clock so phi accrual is a
pure function of the schedule, never of host load; MTTR is still measured
with perf_counter.

Seed scanning: the loss schedules are pure murmur3 functions of (seed,
step, shard), so tests SCAN for a seed whose schedule has the shape they
need (exactly one loss, mid-horizon, on the last shard) instead of
hardcoding magic seeds — the predicate documents the scenario. The
last-shard constraint is load-bearing: failover rewinds the observed step
counter to the journal frontier, so a loss scheduled for a LOW shard
index would re-fire when the rebuilt (renumbered) mesh re-crosses that
step. Shard 3 of a 4-shard mesh stops existing after the rebuild; the
mid-backoff test extends the same reasoning to a 2-loss 3->2 cascade.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from akka_tpu.batched import Emit, behavior
from akka_tpu.batched.bridge import RecoveredAskLost
from akka_tpu.batched.sentinel import (MeshSentinel, SentinelHalted,
                                       ShardProgressMonitor)
from akka_tpu.batched.sharded import ShardedBatchedSystem
from akka_tpu.batched.supervision import ATT_PROGRESS, ATT_STEP, ATT_WORDS
from akka_tpu.event.flight_recorder import InMemoryFlightRecorder
from akka_tpu.pattern.ask import AskTimeoutException
from akka_tpu.pattern.circuit_breaker import (CircuitBreaker,
                                              CircuitBreakerOpenException)
from akka_tpu.remote.failure_detector import PhiAccrualFailureDetector
from akka_tpu.testkit import chaos

P = 4
N = 8          # actors
CAP = 48       # divisible by 4, 3, 2, 1: survives any eviction cascade
NDEV = 4
DT = 0.1       # manual-clock seconds per drive iteration

# detector tuning shared by every sentinel in this file: ~4 frozen
# observations at DT cadence push phi past 3.0 (docs/FAILOVER.md)
DETECT = dict(detector_threshold=3.0, heartbeat_interval=DT,
              acceptable_pause=3 * DT)


def make_sum(name="sum"):
    @behavior(name, {"total": ((), jnp.float32)})
    def summer(state, inbox, ctx):
        return {"total": state["total"] + inbox.sum[0]}, Emit.none(1, P)

    return summer


def make_echo(name="echo"):
    """Replies 2x the request's column 0 to the reply row carried in the
    LAST payload column (the ask convention)."""

    @behavior(name, {"seen": ((), jnp.float32)})
    def echo(state, inbox, ctx):
        reply_to = inbox.sum[P - 1].astype(jnp.int32)
        return ({"seen": state["seen"] + inbox.sum[0]},
                Emit.single(reply_to,
                            jnp.stack([inbox.sum[0] * 2.0, 0.0, 0.0, 0.0]),
                            1, P, when=inbox.count > 0))

    return echo


def tell_schedule(seed, n, steps, every=3):
    sched = {}
    for s in range(steps):
        if s % every == 0:
            sched[s] = (int(chaos.chaos_hash(seed, s, 0) % n),
                        float(1 + s % 5))
    return sched


def sum_oracle(sched, n, upto):
    """A tell staged at host step c is delivered by dispatch c+1."""
    out = np.zeros(n, np.float32)
    for s, (dst, val) in sched.items():
        if s <= upto - 1:
            out[dst] += val
    return out


def drive(sent, sched, upto, staged, clk=None, chunk=1, base=0):
    """Step `sent` to host step `upto`, staging scheduled tells at their
    step counters. `staged` persists ACROSS failovers: a failover rewinds
    host_step to the journal frontier and the WAL replay re-stages every
    journaled tell, so the drive loop must not re-tell schedule entries it
    already staged. chunk > 1 exercises the undrained pipeline window
    (drains retire while later programs are already in flight)."""
    while sent.host_step < upto:
        hs = sent.host_step
        if hs in sched and hs not in staged:
            dst, val = sched[hs]
            pl = np.zeros(P, np.float32)
            pl[0] = val
            sent.tell(base + dst, pl)
            staged.add(hs)
        nxt = min([s for s in sched if s > hs and s not in staged] + [upto])
        k = max(1, min(chunk, nxt - hs, upto - hs))
        if clk is not None:
            clk["t"] += DT * k
        sent.step(k)


def pick_single_loss_seed(horizon, rate=0.012, lo=6, hi=16):
    """Seed whose only scheduled loss in the horizon hits the LAST shard
    mid-run (see module docstring for why the last shard)."""
    for seed in range(30000):
        g = chaos.loss_schedule_np(seed, horizon + 1, NDEV, rate)
        hits = np.argwhere(g)
        if (len(hits) == 1 and hits[0][1] == NDEV - 1
                and lo <= hits[0][0] <= hi):
            return seed, int(hits[0][0])
    raise AssertionError("no single-loss seed in scan range")


def make_sentinel(tmp_path, tag, b, clk=None, backend=None, injector=None,
                  fr=None, **kw):
    args = dict(checkpoint_dir=str(tmp_path / tag), n_devices=NDEV,
                payload_width=P, checkpoint_interval_steps=4,
                pipeline_depth=2, failover_min_backoff=0.35,
                delivery_backend=backend, flight_recorder=fr,
                injector=injector, **DETECT)
    if clk is not None:
        args["clock"] = lambda: clk["t"]
    args.update(kw)
    return MeshSentinel(CAP, [b], **args)


# ----------------------------------------------------- chaos schedule parity
def test_loss_schedule_jnp_np_bit_identical():
    for seed in (0, 7, 62, 334, 1999):
        for rate in (0.0, 0.01, 0.2, 1.0):
            j = np.asarray(chaos.loss_schedule(seed, 24, NDEV, rate))
            n = chaos.loss_schedule_np(seed, 24, NDEV, rate)
            np.testing.assert_array_equal(j, n)
            # stall schedule shares the primitive under a different salt
            js = np.asarray(chaos.loss_schedule(seed, 24, NDEV, rate,
                                                salt=chaos.STALL_SALT))
            ns = chaos.loss_schedule_np(seed, 24, NDEV, rate,
                                        salt=chaos.STALL_SALT)
            np.testing.assert_array_equal(js, ns)


def test_disabled_injector_is_identity():
    att = np.arange(NDEV * ATT_WORDS, dtype=np.int64).reshape(NDEV,
                                                              ATT_WORDS)
    off = chaos.DeviceLossInjector(62, NDEV, loss_rate=0.9, stall_rate=0.9,
                                   enabled=False)
    assert off.filter_attention(att) is att  # not even a copy
    zero = chaos.DeviceLossInjector(62, NDEV)
    assert zero.filter_attention(att) is att


def test_injector_freezes_lost_shard_and_thaws_stall():
    seed, t1 = pick_single_loss_seed(horizon=30)
    inj = chaos.DeviceLossInjector(seed, NDEV, loss_rate=0.012)
    rows = []
    for step in range(t1 + 4):
        att = np.zeros((NDEV, ATT_WORDS), np.int64)
        att[:, ATT_STEP] = step
        att[:, ATT_PROGRESS] = step
        rows.append(inj.filter_attention(att))
    # the dying step's completion never reaches the host: the row froze at
    # the last observation BEFORE the scheduled loss step...
    assert rows[-1][NDEV - 1, ATT_PROGRESS] == t1 - 1
    # ...healthy shards pass through untouched
    np.testing.assert_array_equal(rows[-1][: NDEV - 1, ATT_PROGRESS],
                                  np.full(NDEV - 1, t1 + 3))

    # a stall freezes for stall_steps observed steps, then thaws
    sseed = next(s for s in range(10000)
                 if chaos.loss_schedule_np(s, 10, NDEV, 0.02,
                                           salt=chaos.STALL_SALT)[4, 1]
                 and chaos.loss_schedule_np(s, 20, NDEV, 0.02,
                                            salt=chaos.STALL_SALT).sum() == 1)
    stall = chaos.DeviceLossInjector(sseed, NDEV, stall_rate=0.02,
                                     stall_steps=3)
    seen = []
    for step in range(12):
        att = np.zeros((NDEV, ATT_WORDS), np.int64)
        att[:, ATT_STEP] = step
        att[:, ATT_PROGRESS] = step
        seen.append(int(stall.filter_attention(att)[1, ATT_PROGRESS]))
    assert seen[4] == seen[5] == seen[6] == 3   # frozen window [4, 6]
    assert seen[7] == 7                          # thawed


# ---------------------------------------------------------- quiet-path parity
@pytest.mark.parametrize("backend", [None, "reference"])
def test_quiet_parity_disabled_injector(tmp_path, backend):
    """A disabled injector (and an armed-but-never-firing sentinel) is
    bit-invisible: same totals, same attention words, same counters as a
    sentinel with no injector at all."""
    seed, horizon = 5, 12
    sched = tell_schedule(seed, N, horizon)
    off = chaos.DeviceLossInjector(62, NDEV, loss_rate=0.9, enabled=False)
    runs = []
    for tag, inj in (("armed", off), ("bare", None)):
        clk = {"t": 0.0}
        s = make_sentinel(tmp_path, f"{tag}-{backend}", make_sum(), clk=clk,
                          backend=backend, injector=inj)
        rows = s.spawn(0, N)
        drive(s, sched, horizon, set(), clk=clk)
        runs.append((np.asarray(s.read_state("total", rows)),
                     np.asarray(jax.device_get(s.system.attention)),
                     np.asarray(s.system.dropped_per_shard),
                     np.asarray(s.system.mailbox_overflow_per_shard),
                     s.sentinel_stats()["failovers"]))
        s.shutdown()
    for a, b in zip(runs[0], runs[1]):
        np.testing.assert_array_equal(a, b)
    assert runs[0][4] == 0
    np.testing.assert_array_equal(runs[0][0], sum_oracle(sched, N, horizon))


# -------------------------------------------------- phi detector (satellite 1)
def test_phi_default_clock_is_monotonic():
    # wall-clock (time.time) is NTP-steerable; the detector must default
    # to the monotonic clock so a clock jump cannot fake a failure
    assert PhiAccrualFailureDetector().clock is time.monotonic
    assert ShardProgressMonitor().clock is time.monotonic


def test_phi_manual_clock_ntp_jump_regression():
    clk = {"t": 0.0}
    fd = PhiAccrualFailureDetector(threshold=3.0, min_std_deviation=0.025,
                                   acceptable_heartbeat_pause=0.3,
                                   first_heartbeat_estimate=0.1,
                                   clock=lambda: clk["t"])
    for _ in range(20):
        fd.heartbeat()
        clk["t"] += 0.1
    # steady cadence on the injected clock: available, phi calm — and a
    # wall-clock jump CANNOT reach this detector, because it never reads
    # wall time (the jump below is what an NTP step would do to a
    # wall-clock-backed detector, proving why the default is monotonic)
    assert fd.is_available and fd.phi() < 1.0
    clk["t"] += 3600.0
    assert not fd.is_available and fd.phi() > 3.0


# --------------------------------------------- circuit breaker (satellite 2)
def test_half_open_admits_exactly_one_probe_and_reopens_atomically():
    cb = CircuitBreaker(None, max_failures=1, call_timeout=10.0,
                        reset_timeout=0.05, exponential_backoff_factor=2.0,
                        max_reset_timeout=10.0)
    with pytest.raises(RuntimeError):
        cb.call(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    assert cb.state == "open"
    time.sleep(0.06)
    assert cb.state == "half-open"

    probe_started = threading.Event()
    outcomes = {}

    def probe():
        probe_started.set()
        time.sleep(0.15)  # hold the permit while the rival attempts
        raise RuntimeError("probe fails")

    def run_probe():
        try:
            cb.call(probe)
        except Exception as e:  # noqa: BLE001
            outcomes["probe"] = e

    def run_rival():
        probe_started.wait(2.0)
        try:
            cb.call(lambda: outcomes.setdefault("rival_ran", True))
        except Exception as e:  # noqa: BLE001
            outcomes["rival"] = e

    t1 = threading.Thread(target=run_probe)
    t2 = threading.Thread(target=run_rival)
    t1.start(); t2.start(); t1.join(); t2.join()

    # exactly ONE probe was admitted; the rival failed fast on the permit
    assert "rival_ran" not in outcomes
    assert isinstance(outcomes["rival"], CircuitBreakerOpenException)
    assert isinstance(outcomes["probe"], RuntimeError)
    # the raising probe re-opened atomically: backoff doubled AND the
    # reset timer restarted (remaining > the original 0.05s budget)
    assert cb.state == "open"
    assert cb._current_reset == pytest.approx(0.1)
    with pytest.raises(CircuitBreakerOpenException) as ei:
        cb.call(lambda: None)
    assert ei.value.remaining > 0.05


# ------------------------------------------- per-shard overflow (satellite 3)
@pytest.mark.parametrize("n_dev", [2, 4])
def test_per_shard_overflow_counters_and_event(n_dev):
    n = 64

    @behavior("spam", {}, always_on=True)
    def spam(state, inbox, ctx):
        return {}, Emit.single(0, jnp.array([1.0, 0, 0, 0]), 1, 4)

    fr = InMemoryFlightRecorder()
    sys_ = ShardedBatchedSystem(capacity=n, behaviors=[spam],
                                n_devices=n_dev, remote_capacity_per_pair=2)
    sys_.flight_recorder = fr
    sys_.spawn_block(spam, n)
    sys_.run(3)
    word = sys_.read_attention()
    per_shard = np.asarray(sys_.dropped_per_shard)
    assert per_shard.shape == (n_dev,)
    assert per_shard.sum() == sys_.total_dropped > 0
    np.testing.assert_array_equal(per_shard, word["dropped_per_shard"])
    assert sys_.mailbox_overflow_per_shard.shape == (n_dev,)
    events = fr.of_type("shard_overflow")
    assert events, "overflow growth must emit a shard_overflow warning"
    assert {e["shard"] for e in events} <= set(range(n_dev))
    assert all(e["dropped"] > 0 for e in events)
    n_first = len(events)
    sys_.read_attention()  # no growth since last read -> no new events
    assert len(fr.of_type("shard_overflow")) == n_first


# --------------------------------------------------- the tentpole acceptance
@pytest.mark.parametrize("backend,phase", [(None, "staging"),
                                           ("reference", "pipeline-full")])
def test_auto_failover_bit_parity(tmp_path, backend, phase):
    """Chaos kills a shard mid-run; the sentinel detects it from the frozen
    progress lane, evicts, rebuilds on 3 devices from snapshot + WAL, and
    finishes BIT-IDENTICAL to an uninterrupted twin and the numpy oracle —
    no manual restore call anywhere."""
    horizon = 40
    seed, t1 = pick_single_loss_seed(horizon)
    sched = tell_schedule(seed, N, horizon)
    chunk = 1 if phase == "staging" else 3

    clk = {"t": 0.0}
    fr = InMemoryFlightRecorder()
    inj = chaos.DeviceLossInjector(seed, NDEV, loss_rate=0.012)
    victim = make_sentinel(tmp_path, f"victim-{backend}-{phase}", make_sum(),
                           clk=clk, backend=backend, injector=inj, fr=fr,
                           pipeline_depth=(3 if phase == "pipeline-full"
                                           else 2))
    vrows = victim.spawn(0, N)
    drive(victim, sched, horizon, set(), clk=clk, chunk=chunk)

    stats = victim.sentinel_stats()
    assert stats["failovers"] == 1 and stats["halted"] is None
    assert len(victim.devices) == NDEV - 1
    assert victim.system.n_shards == NDEV - 1
    st = victim.failover_stats[0]
    assert st["lost_shards"] == [NDEV - 1]
    assert st["detector"] == "phi-accrual"
    assert st["evicted_at_step"] >= t1  # cannot evict before the loss fires
    assert st["mttr_s"] is not None and st["mttr_s"] > 0
    names = [e["event"] for e in fr.events()]
    for ev in ("device_suspected", "device_evicted", "failover_completed"):
        assert ev in names

    # uninterrupted twin (identical machinery, no injector) and the oracle
    tclk = {"t": 0.0}
    twin = make_sentinel(tmp_path, f"twin-{backend}-{phase}", make_sum(),
                         clk=tclk, backend=backend,
                         pipeline_depth=(3 if phase == "pipeline-full"
                                         else 2))
    trows = twin.spawn(0, N)
    drive(twin, sched, horizon, set(), clk=tclk, chunk=chunk)
    assert twin.sentinel_stats()["failovers"] == 0

    truth = np.asarray(twin.read_state("total", trows))
    np.testing.assert_array_equal(truth, sum_oracle(sched, N, horizon))
    got = np.asarray(victim.read_state("total", vrows))
    np.testing.assert_array_equal(got, truth)
    # the degraded mesh keeps heartbeating: 3 live progress lanes
    word = victim.read_attention()
    assert word["progress_per_shard"].shape == (NDEV - 1,)
    assert (word["progress_per_shard"] > 0).all()
    victim.shutdown()
    twin.shutdown()


def test_mid_backoff_second_loss_cascades_to_two_devices(tmp_path):
    """A second loss landing inside the post-failover backoff window is
    DEFERRED (suspicion withdrawn, no event), then acted on once the
    window closes: 4 -> 3 -> 2 devices, depth degraded, still oracle-exact."""
    horizon, rate = 60, 0.012
    seed = t1 = t2 = None
    for cand in range(30000):
        g = chaos.loss_schedule_np(cand, horizon + 1, NDEV, rate)
        hits = sorted((int(t), int(s)) for t, s in np.argwhere(g))
        if (len(hits) == 2 and hits[0][1] == 3 and hits[1][1] == 2
                and 6 <= hits[0][0] <= 14
                and hits[0][0] + 10 <= hits[1][0] <= hits[0][0] + 16):
            seed, t1, t2 = cand, hits[0][0], hits[1][0]
            break
    assert seed is not None
    sched = tell_schedule(seed, N, horizon)

    clk = {"t": 0.0}
    fr = InMemoryFlightRecorder()
    inj = chaos.DeviceLossInjector(seed, NDEV, loss_rate=rate)
    s = make_sentinel(tmp_path, "cascade", make_sum(), clk=clk, injector=inj,
                      fr=fr, failover_min_backoff=1.2, max_failovers=5)
    rows = s.spawn(0, N)
    drive(s, sched, horizon, set(), clk=clk)

    stats = s.sentinel_stats()
    assert stats["failovers"] == 2 and stats["halted"] is None
    assert len(s.devices) == 2 and s.system.n_shards == 2
    # deferral emitted NO extra suspicion events: one per acted-on loss
    assert len(fr.of_type("device_suspected")) == 2
    assert [e["shard"] for e in fr.of_type("device_evicted")] == [3, 2]
    # the second eviction waited out the backoff window (deferred, then
    # acted on): at least backoff_delay(1, 1.2, ...) = 2.4 clock-seconds
    # separate the failovers even though the loss fired well inside it
    f1, f2 = s.failover_stats
    assert f2["at_clock"] - f1["at_clock"] >= 2.4
    assert f2["pipeline_depth"] < f1["pipeline_depth"]  # degrade ladder
    np.testing.assert_array_equal(np.asarray(s.read_state("total", rows)),
                                  sum_oracle(sched, N, horizon))
    s.shutdown()


# ------------------------------------------------------------- ask semantics
def test_ask_resolves_times_out_and_fails_fast_on_failover(tmp_path):
    clk = {"t": 0.0}
    echo = make_echo()
    s = make_sentinel(tmp_path, "ask", echo, clk=clk, promise_rows=8)
    rows = s.spawn(0, N)

    fut = s.ask(int(rows[2]), np.array([21.0, 0, 0], np.float32),
                timeout=50.0)
    clk["t"] += 2 * DT
    s.step(2)  # deliver, reply, latch, drain-resolve
    assert fut.done() and float(fut.result()[0]) == 42.0

    # timeout: target row N-1 never replies (asks to a dead row must not
    # hang) — the sentinel clock drives the deadline
    dead_fut = s.ask(int(rows[0]) + CAP // 2, np.array([1.0], np.float32),
                     timeout=0.5)
    for _ in range(8):
        clk["t"] += DT
        s.step(1)
    assert isinstance(dead_fut.exception(), AskTimeoutException)

    # failover: an outstanding ask fails FAST with RecoveredAskLost
    lost_fut = s.ask(int(rows[3]), np.array([7.0, 0, 0], np.float32),
                     timeout=50.0)
    s.force_evict([NDEV - 1])
    assert isinstance(lost_fut.exception(), RecoveredAskLost)
    # the rebuilt system still answers fresh asks
    fut2 = s.ask(int(rows[2]), np.array([4.0, 0, 0], np.float32),
                 timeout=50.0)
    clk["t"] += 2 * DT
    s.step(2)
    assert float(fut2.result()[0]) == 8.0
    s.shutdown()


# ------------------------------------------------------ degrade-to-halt path
def test_repeated_failovers_trip_breaker_into_halt(tmp_path):
    clk = {"t": 0.0}
    fr = InMemoryFlightRecorder()
    s = make_sentinel(tmp_path, "halt", make_sum(), clk=clk, fr=fr,
                      max_failovers=2, pipeline_depth=4,
                      failover_min_backoff=0.01)
    rows = s.spawn(0, N)
    s.tell(int(rows[0]), np.array([1.0, 0, 0, 0], np.float32))
    s.step(2)

    s.force_evict([3])     # failover 1: 4 -> 3
    assert s.pipeline_depth == 4
    s.step(1)
    s.force_evict([2])     # failover 2: 3 -> 2, depth halves, breaker trips
    assert s.pipeline_depth == 2
    assert len(s.devices) == 2
    s.step(1)

    s.force_evict([1])     # breaker open: degrade to HALT, not failover 3
    assert s.halted is not None
    assert s.sentinel_stats()["failovers"] == 2
    halted = fr.of_type("failover_halted")
    assert len(halted) == 1 and halted[0]["failovers"] == 2
    with pytest.raises(SentinelHalted):
        s.step(1)
    with pytest.raises(SentinelHalted):
        s.tell(int(rows[0]), np.array([1.0, 0, 0, 0], np.float32))
    s.shutdown()


# ------------------------------------------------- deadline lane (hung pump)
def test_monitor_deadline_suspects_stalest_shard():
    clk = {"t": 0.0}
    mon = ShardProgressMonitor(threshold=3.0, heartbeat_interval=0.1,
                               acceptable_pause=0.3,
                               clock=lambda: clk["t"])
    att = np.zeros((NDEV, ATT_WORDS), np.int64)
    for step in range(1, 6):
        att[:, ATT_PROGRESS] = step
        att[2, ATT_PROGRESS] = 1  # shard 2 lags from the start
        assert mon.observe(att) == []
        clk["t"] += 0.1
    assert mon.check_deadline() is None  # observations are flowing
    # total drain silence: no observe() at all past the deadline — phi has
    # no new words to accrue on, only the wall clock can see this
    clk["t"] += 10.0
    hit = mon.check_deadline()
    assert hit is not None
    shard, phi, detector = hit
    assert shard == 2 and detector == "deadline"  # stalest lane is blamed
    assert mon.check_deadline() is None  # suspicion fires once
    mon.reset()
    assert mon.suspected() == set()


def test_monitor_unsuspect_defers_then_retrips():
    """The backoff-window deferral contract: withdrawn suspicion re-trips
    on the next observation while the lane is still frozen."""
    clk = {"t": 0.0}
    mon = ShardProgressMonitor(threshold=3.0, heartbeat_interval=0.1,
                               acceptable_pause=0.3,
                               clock=lambda: clk["t"])
    att = np.zeros((NDEV, ATT_WORDS), np.int64)
    newly = []
    for step in range(1, 12):
        att[:, ATT_PROGRESS] = step
        att[1, ATT_PROGRESS] = min(step, 2)  # shard 1 freezes at step 2
        clk["t"] += 0.1
        newly = mon.observe(att)
        if newly:
            break
    assert [s for s, _, _ in newly] == [1]
    assert mon.observe(att) == []        # suspicion latches: no re-report
    mon.unsuspect([1])                   # deferred by the backoff window
    clk["t"] += 0.1
    again = mon.observe(att)             # still frozen: trips again
    assert [s for s, _, _ in again] == [1]


def test_sentinel_poll_drives_deadline_eviction(tmp_path):
    clk = {"t": 0.0}
    fr = InMemoryFlightRecorder()
    s = make_sentinel(tmp_path, "poll", make_sum(), clk=clk, fr=fr)
    s.spawn(0, N)
    for _ in range(3):
        clk["t"] += DT
        s.step(1)
    s.poll()
    assert s.sentinel_stats()["failovers"] == 0  # healthy: poll is a no-op
    clk["t"] += 10.0  # pump goes silent past the deadline
    s.poll()
    assert s.sentinel_stats()["failovers"] == 1
    assert fr.of_type("device_suspected")[0]["detector"] == "deadline"
    assert len(s.devices) == NDEV - 1
    s.shutdown()


# ------------------------------------------------------------ config surface
def test_config_wires_sentinel_keys(tmp_path):
    from akka_tpu.config import Config, reference_config
    from akka_tpu.dispatch.batched import TpuBatchedDispatcher

    class _Disp:
        pass

    ref = reference_config()
    base = "akka.actor.tpu-dispatcher"
    assert ref.get_float(f"{base}.sentinel-threshold", 0.0) == 8.0
    assert ref.get_int(f"{base}.sentinel-max-failovers", 0) == 3

    cfg = Config({"capacity": 64, "payload-width": 8, "promise-rows": 8,
                  "sentinel-threshold": 5.5,
                  "sentinel-heartbeat-interval": "50ms",
                  "sentinel-acceptable-pause": "2s",
                  "sentinel-max-failovers": 7})
    d = TpuBatchedDispatcher(_Disp(), "tpu-dispatcher", cfg)
    h = d.handle()
    assert h._sentinel.threshold == 5.5
    assert h._sentinel.heartbeat_interval == pytest.approx(0.05)
    assert h._sentinel.acceptable_pause == pytest.approx(2.0)
    assert h.sentinel_max_failovers == 7
    assert h.sentinel_stats()["max_failovers"] == 7
    h.shutdown()
