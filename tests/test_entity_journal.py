"""Durable entity layer (persistence/entity_journal.py + the
sharding/device.py hooks, ISSUE 15): wave-granular group commit of
per-entity events, snapshot piggybacking, torn-tail truncation, compaction,
open-time replay, and the region-level contract — a journaled region is
bit-identical to an undisturbed twin, and a crash-restored twin reproduces
the exact acked per-entity state.

Tier-1 budget: the journal unit tests are host-only file I/O; the region
tests ride the SAME spec shape as test_ask_batch (2 shards x 16 eps, one
virtual device, payload width 4) so the jit cache is warm and no wave
exceeds 64 rows. The append-overhead test is a loose absolute bound on
pure file I/O — it guards against an accidental per-event fsync creeping
into the group-commit path, not against disk jitter.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from akka_tpu.event.flight_recorder import InMemoryFlightRecorder
from akka_tpu.event.metrics import MetricsRegistry
from akka_tpu.persistence import EntityJournal, OP_ADD
from akka_tpu.gateway import counter_behavior
from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion


# ------------------------------------------------------------ journal unit
def test_append_fold_totals_and_stats(tmp_path):
    ej = EntityJournal(str(tmp_path / "e.journal"))
    assert ej.append_wave(1, [("a", OP_ADD, 2.0), ("b", OP_ADD, 3.0)]) == 2
    assert ej.append_wave(2, [("a", OP_ADD, 1.0)]) == 1
    assert ej.append_wave(3, []) == 0  # all-get wave: no record at all
    assert ej.totals() == {"a": 3.0, "b": 3.0}
    st = ej.stats()
    assert st["waves"] == 2 and st["events"] == 3
    # fsync_every_n=1 default: one group commit per non-empty wave
    assert st["fsyncs"] == 2
    assert len(ej.records()) == 2  # ONE record per wave, not per event
    ej.close()


def test_reopen_replays_snapshot_plus_event_tail(tmp_path):
    path = str(tmp_path / "e.journal")
    ej = EntityJournal(path, snapshot_every=3)
    for step in range(5):  # entity "a" crosses snapshot_every at wave 3
        ej.append_wave(step, [("a", OP_ADD, 1.0), ("b", OP_ADD, 10.0)])
    ej.close()
    twin = EntityJournal(path, snapshot_every=3)
    assert twin.totals() == {"a": 5.0, "b": 50.0}
    # the snap at wave 3 resets the tail: replay folded 5 events but the
    # per-entity tail the NEXT snapshot decision sees is 2
    assert twin.replayed_events() == {"a": 5, "b": 5}
    twin.close()


def test_snapshot_piggybacks_into_the_same_record(tmp_path):
    ej = EntityJournal(str(tmp_path / "e.journal"), snapshot_every=2)
    ej.append_wave(1, [("a", OP_ADD, 1.0)])
    ej.append_wave(2, [("a", OP_ADD, 2.0)])  # 2nd event -> snap rides along
    recs = ej.records()
    assert recs[0]["snaps"] == {} and recs[1]["snaps"] == {"a": 3.0}
    assert ej.stats()["snaps"] == 1 and ej.stats()["fsyncs"] == 2
    ej.close()


def test_torn_tail_truncated_and_flight_recorded(tmp_path):
    path = str(tmp_path / "e.journal")
    ej = EntityJournal(path)
    ej.append_wave(1, [("a", OP_ADD, 4.0)])
    ej.close()
    with open(path, "ab") as f:  # a wave the crash tore mid-write
        f.write((1 << 20).to_bytes(8, "little"))
        f.write(b"torn")
    fr = InMemoryFlightRecorder()
    twin = EntityJournal(path, flight_recorder=fr)
    assert twin.truncated_bytes > 0
    assert twin.totals() == {"a": 4.0}
    assert fr.of_type("journal_truncated")
    twin.close()


def test_compact_rewrites_one_snap_all_record(tmp_path):
    path = str(tmp_path / "e.journal")
    ej = EntityJournal(path)
    for step in range(6):
        ej.append_wave(step, [(f"e{step % 3}", OP_ADD, float(step))])
    before = ej.totals()
    assert ej.compact() == 3
    recs = ej.records()
    assert len(recs) == 1 and recs[0]["events"] == []
    assert ej.totals() == before
    # post-compact appends fold on top and survive reopen
    ej.append_wave(9, [("e0", OP_ADD, 1.0)])
    ej.close()
    twin = EntityJournal(path)
    assert twin.totals()["e0"] == before["e0"] + 1.0
    # replay folds only the post-compact tail, not history
    assert twin.replayed_events() == {"e0": 1}
    twin.close()


def test_auto_compaction_bounds_the_file(tmp_path):
    ej = EntityJournal(str(tmp_path / "e.journal"), snapshot_every=4,
                       compact_every=8)
    for step in range(9):
        ej.append_wave(step, [("a", OP_ADD, 1.0)])
    assert ej.stats()["compactions"] >= 1
    assert ej.totals() == {"a": 9.0}
    ej.close()


def test_per_event_fsync_degenerate_leg(tmp_path):
    """The bench A/B's 'per-entity sync write' leg: one record + one
    fsync per EVENT instead of one per wave."""
    ej = EntityJournal(str(tmp_path / "e.journal"))
    ej.append_wave(1, [("a", OP_ADD, 1.0), ("b", OP_ADD, 2.0),
                       ("c", OP_ADD, 3.0)], per_event_fsync=True)
    assert len(ej.records()) == 3
    assert ej.stats()["fsyncs"] == 3
    assert ej.totals() == {"a": 1.0, "b": 2.0, "c": 3.0}
    ej.close()


def test_group_commit_fsync_every_n_waves(tmp_path):
    ej = EntityJournal(str(tmp_path / "e.journal"), fsync_every_n=4)
    for step in range(7):
        ej.append_wave(step, [("a", OP_ADD, 1.0)])
    assert ej.stats()["fsyncs"] == 1  # wave 4 only; 3 pending
    ej.sync()
    assert ej.stats()["fsyncs"] == 2
    ej.close()


def test_journal_metrics_histograms(tmp_path):
    reg = MetricsRegistry()
    reg.set_step(7)
    ej = EntityJournal(str(tmp_path / "e.journal"), registry=reg)
    ej.append_wave(1, [("a", OP_ADD, 1.0), ("b", OP_ADD, 2.0)])
    batch = reg.histogram("entity_journal_batch_size").snapshot()
    assert batch["count"] == 1 and batch["sum"] == 2.0
    assert reg.histogram("entity_journal_fsync_ms").snapshot()["count"] == 1
    ej.close()
    twin = EntityJournal(str(tmp_path / "e.journal"), registry=reg)
    # replay histogram: one observation per entity, value = tail length
    assert reg.histogram("entity_replay_events").snapshot()["count"] == 2
    twin.close()


def test_journal_append_overhead_budget(tmp_path):
    """Smoke budget: 256 group-committed waves of 16 events must stay
    far under the ask-wave cadence. Loose absolute bound (pure file
    I/O) — catches an accidental per-event fsync, not disk jitter."""
    ej = EntityJournal(str(tmp_path / "e.journal"), fsync_every_n=64)
    events = [(f"e{i}", OP_ADD, 1.0) for i in range(16)]
    t0 = time.perf_counter()
    for step in range(256):
        ej.append_wave(step, events)
    dt = time.perf_counter() - t0
    ej.close()
    assert dt < 2.0, f"256 waves took {dt:.2f}s"


# ---------------------------------------------------------- region parity
_SPEC_KW = dict(n_shards=2, entities_per_shard=16, n_devices=1,
                payload_width=4)


def _drive(region, seq):
    """One ask wave per (entity, value) batch; returns acked totals."""
    acked = {}
    for batch in seq:
        refs = [region.entity_ref(e) for e, _v in batch]
        outs = region.ask_many([(r.shard, r.index, [v])
                                for r, (_e, v) in zip(refs, batch)])
        for (e, _v), out in zip(batch, outs):
            assert not isinstance(out, BaseException), out
            acked[e] = float(np.asarray(out)[0])
    return acked


_SEQ = [[("ej-a0", 2.0), ("ej-a1", 3.0), ("ej-a2", 5.0)],
        [("ej-a0", 1.0), ("ej-a3", 7.0)],
        [("ej-a1", 4.0), ("ej-a2", 0.25), ("ej-a4", 9.0)]]


def test_journaled_region_bit_identical_to_undisturbed_twin(tmp_path):
    """The durable layer must be a pure observer of the wave: a region
    with the entity journal armed produces bit-identical replies and
    state to a twin without it — and the journal's fold equals the acked
    totals, one group-committed record per wave."""
    fr = InMemoryFlightRecorder()
    a = DeviceShardRegion(DeviceEntity("ej-par-a", counter_behavior(4),
                                       **_SPEC_KW))
    a.system.flight_recorder = fr
    a.attach_journal(str(tmp_path / "a"))
    a.attach_entity_journal(str(tmp_path / "a"))
    b = DeviceShardRegion(DeviceEntity("ej-par-b", counter_behavior(4),
                                       **_SPEC_KW))
    acked_a = _drive(a, _SEQ)
    acked_b = _drive(b, _SEQ)
    assert acked_a == acked_b
    ej = a._entity_journal
    assert ej.totals() == acked_a
    st = ej.stats()
    assert st["waves"] == len(_SEQ)  # ONE record per ask wave
    assert st["events"] == sum(len(w) for w in _SEQ)
    committed = fr.of_type("entity_events_committed")
    assert [e["n"] for e in committed] == [len(w) for w in _SEQ]
    a.detach_entity_journal()


def test_crash_restore_replays_exact_acked_state(tmp_path):
    """kill -9 analogue in one process: a fresh identically-spec'd region
    pointed at the journal dir restores, respawns every remembered
    entity with ZERO traffic, and its per-entity durable state equals
    the original's acked totals exactly."""
    d = str(tmp_path / "r")
    a = DeviceShardRegion(DeviceEntity("ej-res", counter_behavior(4),
                                       **_SPEC_KW))
    a.attach_journal(d)
    a.attach_entity_journal(d)
    a.checkpoint()
    acked = _drive(a, _SEQ)
    # no close/sync call: every wave already fsync'd (fsync_every_n=1)

    fr = InMemoryFlightRecorder()
    c = DeviceShardRegion(DeviceEntity("ej-res", counter_behavior(4),
                                       **_SPEC_KW))
    c.system.flight_recorder = fr
    c.attach_journal(d)
    c.attach_entity_journal(d)
    c.restore()
    # respawned from the store/journal union, not from traffic
    for e, want in acked.items():
        ref = c.entity_ref(e)
        got = float(np.asarray(c.system.read_state(
            "total", np.asarray([ref.row], np.int32)))[0])
        assert got == want, (e, got, want)
    assert c._durable_replayed_totals == acked
    replays = fr.of_type("entity_replayed")
    assert replays and replays[-1]["entities"] == len(acked)
