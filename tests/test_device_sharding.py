"""Device-backed cluster sharding: entities→shards→device rows with
coordinator placement, rebalance as slab copy, cross-shard tells as
all_to_all (VERDICT r1 item 4; reference: ShardRegion.scala:1046,
ShardCoordinator.scala:90-201). Runs on the virtual 8-device CPU mesh."""

import jax.numpy as jnp
import numpy as np

from akka_tpu.batched import Emit, behavior
from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion

P = 4


@behavior("dev-counter", {"n": ((), jnp.int32)})
def dev_counter(state, inbox, ctx):
    return ({"n": state["n"] + inbox.count}, Emit.none(1, P))


def make_forwarder(eps: int, n_shards: int):
    """Entity that forwards its token to the SAME index in the NEXT logical
    shard, resolved through the live placement table — messages follow a
    rebalanced shard wherever it moves."""

    @behavior("dev-fwd", {"received": ((), jnp.int32),
                          "myshard": ((), jnp.int32),
                          "myidx": ((), jnp.int32)})
    def fwd(state, inbox, ctx):
        base = ctx.tables["shard_row_base"]
        nxt_shard = (state["myshard"] + 1) % n_shards
        dst = base[nxt_shard] + state["myidx"]
        return ({"received": state["received"] + inbox.count,
                 "myshard": state["myshard"], "myidx": state["myidx"]},
                Emit.single(dst, inbox.sum, 1, P, when=inbox.count > 0))

    return fwd


def test_entity_allocation_and_tell():
    spec = DeviceEntity("counters", dev_counter, n_shards=8,
                        entities_per_shard=16, payload_width=P)
    region = DeviceShardRegion(spec)
    a = region.entity_ref("alice")
    b = region.entity_ref("bob")
    assert region.entity_ref("alice").row == a.row  # stable resolution
    a.tell([1.0, 0, 0, 0])
    a.tell([1.0, 0, 0, 0])
    b.tell([1.0, 0, 0, 0])
    region.run(1)
    region.block_until_ready()
    assert a.read_state("n") == 2
    assert b.read_state("n") == 1
    st = region.stats()
    assert st["entities"] >= 2 and st["shards"] == 8


def test_shards_spread_over_devices():
    spec = DeviceEntity("spread", dev_counter, n_shards=16,
                        entities_per_shard=8, n_devices=8, payload_width=P)
    region = DeviceShardRegion(spec)
    devs = {region.device_of_shard(s) for s in range(16)}
    assert devs == set(range(8))  # round-robin striping covers the mesh


def test_cross_shard_ring_under_sharding_api():
    n_shards, eps = 16, 8
    fwd = make_forwarder(eps, n_shards)
    spec = DeviceEntity("ring", fwd, n_shards=n_shards,
                        entities_per_shard=eps, n_devices=8, payload_width=P)
    region = DeviceShardRegion(spec)
    region.allocate_all()
    sys = region.system
    # init identity columns + seed one token per entity
    myshard = np.zeros((sys.capacity,), np.int32)
    myidx = np.zeros((sys.capacity,), np.int32)
    for s in range(n_shards):
        base = region.row_of(s, 0)
        myshard[base:base + eps] = s
        myidx[base:base + eps] = np.arange(eps)
    sys.state["myshard"] = sys.state["myshard"].at[:].set(jnp.asarray(myshard))
    sys.state["myidx"] = sys.state["myidx"].at[:].set(jnp.asarray(myidx))
    for s in range(n_shards):
        for i in range(eps):
            sys.tell(region.row_of(s, i), [1.0, 0, 0, 0])
    steps = 5
    region.run(steps)
    region.block_until_ready()
    recv = sys.read_state("received")
    live = np.asarray(sys.alive)
    assert (recv[live] == steps).all()
    assert sys.total_dropped == 0


def test_stray_mode_confined_to_handoff_window():
    """Steady state runs the fast (no-stray-pass) step; a rebalance enters
    stray mode; a single big run() drains the hand-off window and returns
    to the fast step for the remainder, losing nothing (r5: the stray pass
    became a mode after it was attributed as the whole 3x shard-api tax)."""
    n_shards, eps = 8, 8
    fwd = make_forwarder(eps, n_shards)
    region = DeviceShardRegion(DeviceEntity(
        "stray", fwd, n_shards=n_shards, entities_per_shard=eps,
        n_devices=8, payload_width=P))
    region.allocate_all()
    sys = region.system
    myshard = np.zeros((sys.capacity,), np.int32)
    myidx = np.zeros((sys.capacity,), np.int32)
    for s in range(n_shards):
        base = region.row_of(s, 0)
        myshard[base:base + eps] = s
        myidx[base:base + eps] = np.arange(eps)
    sys.state["myshard"] = sys.state["myshard"].at[:].set(jnp.asarray(myshard))
    sys.state["myidx"] = sys.state["myidx"].at[:].set(jnp.asarray(myidx))
    for s in range(n_shards):
        for i in range(eps):
            sys.tell(region.row_of(s, i), [1.0, 0, 0, 0])
    assert sys.stray_mode is False
    base_pair_cap = sys.pair_cap
    region.run(2)
    assert sys.stray_mode is False  # steady state never pays the stray tax

    region.rebalance(2)
    assert sys.stray_mode is True   # hand-off window armed
    region.run(10)                  # drain (3) + steady remainder (7)
    region.block_until_ready()
    assert sys.stray_mode is False  # exited within the same call
    assert sys.pair_cap == base_pair_cap
    # nothing lost across the enter->forward->drain->exit cycle. The
    # forwarding hop delays the token wave by one step; in a window long
    # enough for the wave to lap the ring, EVERY entity downstream misses
    # exactly one delivery (n_shards*eps), and the delayed batch merging
    # with the next at the successor shard costs one more delivery there
    # (eps). Per-entity: exactly nominal-1 (successor shard: nominal-2).
    total = 0
    for s in range(n_shards):
        base = region.row_of(s, 0)
        recv = sys.read_state("received",
                              np.arange(base, base + eps, dtype=np.int32))
        nominal = 12 - 1 - (1 if s == 3 else 0)  # successor of moved shard 2
        assert (recv == nominal).all(), (s, recv)
        total += int(recv.sum())
    assert total == n_shards * eps * 12 - n_shards * eps - eps, total
    assert sys.total_dropped == 0


def test_repeated_rebalances_cycle_stray_mode_losslessly():
    """Five successive rebalances, each triggering a grow->forward->drain->
    shrink cycle of the inbox regridding — the riskiest new path of the
    r5 stray-mode split. VALUE is the conserved quantity (a delayed token
    batch merging with the next in a reduce-mode inbox fuses two MESSAGES
    into one delivery by design — Mailbox.scala reduce semantics — so the
    behavior forwards the SUM and the invariant is value flow): every
    steady-state step must deliver the full circulating value, and
    nothing may drop, across all five cycles."""
    from akka_tpu.batched import Emit, behavior

    n_shards, eps = 8, 8
    total_value = float(n_shards * eps)

    @behavior("valfwd", {"val_seen": ((), jnp.float32),
                         "myshard": ((), jnp.int32),
                         "myidx": ((), jnp.int32)})
    def valfwd(state, inbox, ctx):
        base = ctx.tables["shard_row_base"]
        nxt = (state["myshard"] + 1) % n_shards
        return ({"val_seen": state["val_seen"] + inbox.sum[0],
                 "myshard": state["myshard"], "myidx": state["myidx"]},
                Emit.single(base[nxt] + state["myidx"], inbox.sum, 1, P,
                            when=inbox.count > 0))

    region = DeviceShardRegion(DeviceEntity(
        "reb5", valfwd, n_shards=n_shards, entities_per_shard=eps,
        n_devices=8, payload_width=P))
    region.allocate_all()
    sys = region.system
    myshard = np.zeros((sys.capacity,), np.int32)
    myidx = np.zeros((sys.capacity,), np.int32)
    for s in range(n_shards):
        base = region.row_of(s, 0)
        myshard[base:base + eps] = s
        myidx[base:base + eps] = np.arange(eps)
    sys.state["myshard"] = sys.state["myshard"].at[:].set(jnp.asarray(myshard))
    sys.state["myidx"] = sys.state["myidx"].at[:].set(jnp.asarray(myidx))
    for s in range(n_shards):
        for i in range(eps):
            sys.tell(region.row_of(s, i), [1.0, 0, 0, 0])
    region.run(2)

    def value_seen():
        return sum(float(sys.read_state(
            "val_seen", np.arange(region.row_of(s, 0),
                                  region.row_of(s, 0) + eps,
                                  dtype=np.int32)).sum())
            for s in range(n_shards))

    for k in range(5):
        region.rebalance((k * 3) % n_shards)
        assert sys.stray_mode is True
        region.run(6)  # drain (3) + steady (3)
        region.block_until_ready()
        assert sys.stray_mode is False, f"cycle {k} never exited"
        # steady state after the cycle: each step delivers the FULL
        # circulating value — nothing was lost in grow/forward/shrink
        before = value_seen()
        region.run(4)
        region.block_until_ready()
        assert value_seen() - before == 4 * total_value, (k, before)
    assert sys.total_dropped == 0


def test_slots_mode_rebalance_conserves_value_through_stray_cycle():
    """Ordered per-message mailboxes (slots mode) through a rebalance:
    the stray pass carries the TYPE column through the exchange concat and
    the inbox regrid preserves slot positions — value flow stays exact
    across the grow/forward/drain/shrink cycle."""
    from akka_tpu.batched import Mailbox

    n_shards, eps = 8, 8

    @behavior("slots-val", {"val_seen": ((), jnp.float32),
                            "myshard": ((), jnp.int32),
                            "myidx": ((), jnp.int32)}, inbox="slots")
    def slots_fwd(state, mailbox: Mailbox, ctx):
        inbox = mailbox.reduce()
        base = ctx.tables["shard_row_base"]
        nxt = (state["myshard"] + 1) % n_shards
        return ({"val_seen": state["val_seen"] + inbox.sum[0],
                 "myshard": state["myshard"], "myidx": state["myidx"]},
                Emit.single(base[nxt] + state["myidx"], inbox.sum, 1, P,
                            when=inbox.count > 0))

    region = DeviceShardRegion(DeviceEntity(
        "slots-reb", slots_fwd, n_shards=n_shards, entities_per_shard=eps,
        n_devices=8, payload_width=P, mailbox_slots=2))
    region.allocate_all()
    sys = region.system
    myshard = np.zeros((sys.capacity,), np.int32)
    myidx = np.zeros((sys.capacity,), np.int32)
    for s in range(n_shards):
        base = region.row_of(s, 0)
        myshard[base:base + eps] = s
        myidx[base:base + eps] = np.arange(eps)
    sys.state["myshard"] = sys.state["myshard"].at[:].set(jnp.asarray(myshard))
    sys.state["myidx"] = sys.state["myidx"].at[:].set(jnp.asarray(myidx))
    for s in range(n_shards):
        for i in range(eps):
            sys.tell(region.row_of(s, i), [1.0, 0, 0, 0])
    region.run(2)
    region.block_until_ready()

    region.rebalance(3)
    assert sys.stray_mode is True
    region.run(8)
    region.block_until_ready()
    assert sys.stray_mode is False

    def value_seen():
        return sum(float(sys.read_state(
            "val_seen", np.arange(region.row_of(s, 0),
                                  region.row_of(s, 0) + eps,
                                  dtype=np.int32)).sum())
            for s in range(n_shards))

    before = value_seen()
    region.run(4)
    region.block_until_ready()
    assert value_seen() - before == 4.0 * n_shards * eps, before
    assert sys.total_dropped == 0


def test_rebalance_moves_state_and_messages():
    n_shards, eps = 8, 8
    fwd = make_forwarder(eps, n_shards)
    spec = DeviceEntity("reb", fwd, n_shards=n_shards, entities_per_shard=eps,
                        n_devices=8, payload_width=P)
    region = DeviceShardRegion(spec)
    region.allocate_all()
    sys = region.system
    myshard = np.zeros((sys.capacity,), np.int32)
    myidx = np.zeros((sys.capacity,), np.int32)
    for s in range(n_shards):
        base = region.row_of(s, 0)
        myshard[base:base + eps] = s
        myidx[base:base + eps] = np.arange(eps)
    sys.state["myshard"] = sys.state["myshard"].at[:].set(jnp.asarray(myshard))
    sys.state["myidx"] = sys.state["myidx"].at[:].set(jnp.asarray(myidx))
    for s in range(n_shards):
        for i in range(eps):
            sys.tell(region.row_of(s, i), [1.0, 0, 0, 0])
    region.run(2)
    region.block_until_ready()

    # move shard 3 to a spare block (possibly another device) MID-RUN
    old_dev = region.device_of_shard(3)
    region.rebalance(3)
    moved_dev = region.device_of_shard(3)
    region.run(3)
    region.block_until_ready()

    # No token is ever lost: state followed the move and in-flight messages
    # were re-pointed + forwarded. Accounting: (a) tokens mid-flight toward
    # the moved shard spend one step being forwarded — eps deliveries shift
    # out of the 5-step window; (b) the delayed batch then arrives at the
    # moved shard TOGETHER with the next batch, and a reduce-mode inbox
    # merges them into one delivery (counts sum) — another eps. Every
    # entity still lands within one delivery of nominal and nothing drops.
    total = 0
    for s in range(n_shards):
        base = region.row_of(s, 0)
        recv = sys.read_state("received",
                              np.arange(base, base + eps, dtype=np.int32))
        assert (recv >= 4).all() and (recv <= 5).all(), \
            f"shard {s} (old dev {old_dev} -> {moved_dev}): {recv}"
        total += int(recv.sum())
    assert total == n_shards * eps * 5 - 2 * eps
    assert sys.total_dropped == 0


def test_rebalance_explicit_target_device():
    spec = DeviceEntity("tgt", dev_counter, n_shards=8, entities_per_shard=4,
                        n_devices=8, spare_blocks=8, payload_width=P)
    region = DeviceShardRegion(spec)
    r = region.entity_ref("x")
    r.tell([1.0, 0, 0, 0])
    region.run(1)
    region.block_until_ready()
    assert r.read_state("n") == 1
    target = (region.device_of_shard(r.shard) + 1) % 8
    region.rebalance(r.shard, to_device=target)
    assert region.device_of_shard(r.shard) == target
    # same entity handle keeps working post-move (row resolved via table)
    r.tell([1.0, 0, 0, 0])
    region.run(1)
    region.block_until_ready()
    assert r.read_state("n") == 2


def test_init_device_via_typed_api():
    from akka_tpu import ActorSystem
    from akka_tpu.sharding.typed import ClusterShardingTyped
    system = ActorSystem("devshard", {"akka": {"stdout-loglevel": "OFF"}})
    try:
        sharding = ClusterShardingTyped.get(system)
        spec = DeviceEntity("api-counters", dev_counter, n_shards=4,
                            entities_per_shard=8, payload_width=P)
        region = sharding.init_device(spec)
        assert sharding.device_region("api-counters") is region
        ref = region.entity_ref("e-1")
        ref.tell([1.0, 0, 0, 0])
        region.run(1)
        region.block_until_ready()
        assert ref.read_state("n") == 1
    finally:
        system.terminate()
        system.await_termination(10)


def test_ask_timeout_slot_reclaimed_after_late_reply():
    """A timed-out ask retires its promise slot so the straggler reply
    cannot answer a future ask — but retirement is a parking lot, not a
    leak: once the `__promise_replied` latch shows the late reply landed,
    the slot returns to the free list and asks keep working."""
    from akka_tpu.batched.bridge import reply_dst

    @behavior("late-echo", {"asked": ((), jnp.int32)})
    def echo(state, inbox, ctx):
        return ({"asked": state["asked"] + inbox.count},
                Emit.single(reply_dst(inbox.sum), inbox.sum, 1, P,
                            when=inbox.count > 0))

    region = DeviceShardRegion(DeviceEntity(
        "late-ask", echo, n_shards=4, entities_per_shard=16,
        payload_width=P, host_inbox_per_shard=8))
    region.allocate_all()
    free0 = len(region._promise_free)
    with np.testing.assert_raises(TimeoutError):
        # one step sends the request; the reply is still riding the
        # exchange when the budget runs out
        region.ask(0, 3, [5.0], steps=1, max_extra_steps=0)
    assert len(region._promise_free) == free0 - 1
    assert len(region._promise_retired) == 1  # parked, not dropped

    region.run(4)  # let the straggler reply land in the retired row
    region.block_until_ready()
    assert region._reclaim_promise_slots() == 1
    assert len(region._promise_free) == free0
    assert region._promise_retired == []

    # the recycled pool answers fresh asks with the right payload
    reply = region.ask(0, 3, [7.0, 0, 0])
    assert reply[0] == 7.0
