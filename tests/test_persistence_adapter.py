"""Event/snapshot adapter seam tests (VERDICT r4 missing #4) — modeled on
the reference's EventAdapterSpec (akka-persistence/src/test/.../journal/
EventAdapterSpec.scala: write-side toJournal wrapping, read-side 1->N
upcasting, tagging wrappers) and SnapshotAdapterSpec (persistence-typed:
old-snapshot upcasts through EventSourcedBehavior)."""

import dataclasses

import pytest

from akka_tpu import ActorSystem
from akka_tpu.persistence import (Effect, EventAdapter, EventAdapters,
                                  EventSeq, EventSourcedBehavior, FileJournal,
                                  PersistenceId, RetentionCriteria,
                                  SnapshotAdapter, Tagged)
from akka_tpu.persistence.messages import AtomicWrite, PersistentRepr
from akka_tpu.persistence.persistence import Persistence
from akka_tpu.testkit import TestProbe
from akka_tpu.typed.adapter import props_from_behavior

_ids = [0]


def _plugin_id(name):
    _ids[0] += 1
    return f"test.adapter-{name}-{_ids[0]}"


def _system(journal_plugin_id, snapshot_dir=None):
    snap = {"plugin": "akka.persistence.snapshot-store.local",
            "local": {"dir": snapshot_dir}} if snapshot_dir else \
        {"plugin": "akka.persistence.snapshot-store.inmem"}
    return ActorSystem.create(f"adapter-{_ids[0]}", {
        "akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "persistence": {"journal": {"plugin": journal_plugin_id},
                                 "snapshot-store": snap}}})


# -- domain / journal models --------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ItemAdded:          # domain event
    item: str


@dataclasses.dataclass(frozen=True)
class Wrapped:            # journal model (detached from the domain)
    inner: str


class WrappingAdapter(EventAdapter):
    """domain ItemAdded <-> journal Wrapped (EventAdapterSpec's
    UserDataChanged-style detachment)."""

    def manifest(self, event):
        return "wrapped-v1"

    def to_journal(self, event):
        return Wrapped(event.item)

    def from_journal(self, event, manifest):
        assert manifest == "wrapped-v1"
        return EventSeq.single(ItemAdded(event.inner))


# -- registry unit behavior ---------------------------------------------------

def test_event_adapters_most_specific_class_wins():
    class Base:
        pass

    class Mid(Base):
        pass

    class Leaf(Mid):
        pass

    base_a, mid_a = EventAdapter(), EventAdapter()
    reg = EventAdapters({Base: base_a, Mid: mid_a})
    assert reg.get(Leaf) is mid_a       # nearest ancestor binding
    assert reg.get(Mid) is mid_a
    assert reg.get(Base) is base_a
    assert reg.get(int).to_journal(7) == 7   # unbound -> identity


def test_event_seq_shapes():
    assert EventSeq.empty().events == []
    assert EventSeq.single(1).events == [1]
    assert EventSeq.many([1, 2]).events == [1, 2]


# -- write-side detachment + read-side restore --------------------------------

def test_adapter_detaches_domain_model_and_restores_on_replay(tmp_path):
    d = str(tmp_path / "j")
    pid = _plugin_id("wrap")
    Persistence.register_journal_plugin(
        pid, lambda _s, _c: FileJournal(d))

    def handlers():
        def command_handler(state, cmd):
            if isinstance(cmd, tuple) and cmd[0] == "add":
                return Effect.persist(ItemAdded(cmd[1]))
            return Effect.reply(cmd, tuple(state))

        def event_handler(state, event):
            assert isinstance(event, ItemAdded), event  # domain model only
            return state + [event.item]
        return command_handler, event_handler

    system = _system(pid)
    try:
        Persistence.get(system).register_event_adapters(
            pid, EventAdapters({ItemAdded: WrappingAdapter()}))
        ch, eh = handlers()
        ref = system.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "w1"), [], ch, eh,
            journal_plugin_id=pid)), "cart")
        probe = TestProbe(system)
        ref.tell(("add", "apple"))
        ref.tell(("add", "pear"))
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ("apple", "pear")
    finally:
        system.terminate()
        system.await_termination(10.0)

    # what was STORED is the journal model, not the domain event
    stored = []
    FileJournal(d).replay("Cart|w1", 1, 2**63 - 1, 2**63 - 1, stored.append)
    assert [type(r.payload) for r in stored] == [Wrapped, Wrapped]
    assert [r.manifest for r in stored] == ["wrapped-v1"] * 2

    # a fresh system with the same adapter recovers the DOMAIN model
    system2 = _system(pid)
    try:
        Persistence.get(system2).register_event_adapters(
            pid, EventAdapters({Wrapped: WrappingAdapter(),
                                ItemAdded: WrappingAdapter()}))
        ch, eh = handlers()
        ref = system2.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "w1"), [], ch, eh,
            journal_plugin_id=pid)), "cart")
        probe = TestProbe(system2)
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ("apple", "pear")
    finally:
        system2.terminate()
        system2.await_termination(10.0)


# -- 1 -> N read upcasting ----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BulkAdded:          # legacy journal record (module level: picklable)
    items: tuple


class SplitAdapter(EventAdapter):
    def from_journal(self, event, manifest):
        return EventSeq.many([ItemAdded(i) for i in event.items])


def test_adapter_upcasts_one_stored_record_to_many_events(tmp_path):
    """An old journal holds a combined record; the read adapter fans it out
    (EventAdapter.scala fromJournal EventSeq-many semantics)."""
    d = str(tmp_path / "j")
    old = FileJournal(d)
    assert old.write_atomic(AtomicWrite([
        PersistentRepr(BulkAdded(("a", "b", "c")), 1, "Cart|u1")])) is None

    pid = _plugin_id("split")
    Persistence.register_journal_plugin(pid, lambda _s, _c: FileJournal(d))
    system = _system(pid)
    try:
        Persistence.get(system).register_event_adapters(
            pid, EventAdapters({BulkAdded: SplitAdapter()}))

        def command_handler(state, cmd):
            return Effect.reply(cmd, tuple(state))

        def event_handler(state, event):
            assert isinstance(event, ItemAdded)
            return state + [event.item]

        ref = system.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "u1"), [], command_handler,
            event_handler, journal_plugin_id=pid)), "cart")
        probe = TestProbe(system)
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ("a", "b", "c")
    finally:
        system.terminate()
        system.await_termination(10.0)


# -- tagging wrapper composition ----------------------------------------------

def test_tagging_adapter_composes_with_query(tmp_path):
    """An adapter returning Tagged attaches query tags on the write path
    (the reference's common tagging-adapter pattern)."""
    class TaggingAdapter(EventAdapter):
        def to_journal(self, event):
            return Tagged(Wrapped(event.item), frozenset({"items"}))

        def from_journal(self, event, manifest):
            return EventSeq.single(ItemAdded(event.inner))

    d = str(tmp_path / "j")
    pid = _plugin_id("tag")
    Persistence.register_journal_plugin(pid, lambda _s, _c: FileJournal(d))
    system = _system(pid)
    try:
        Persistence.get(system).register_event_adapters(
            pid, EventAdapters({ItemAdded: TaggingAdapter(),
                                Wrapped: TaggingAdapter()}))

        def command_handler(state, cmd):
            if isinstance(cmd, tuple) and cmd[0] == "add":
                return Effect.persist(ItemAdded(cmd[1]))
            return Effect.reply(cmd, tuple(state))

        def event_handler(state, event):
            return state + [event.item]

        ref = system.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "t1"), [], command_handler,
            event_handler, journal_plugin_id=pid,
            tagger=lambda ev: frozenset({"by-tagger"}))), "cart")
        probe = TestProbe(system)
        ref.tell(("add", "apple"))
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ("apple",)
        plugin = Persistence.get(system).journal_plugin_for(pid)
        # adapter-attached AND typed-tagger tags both reach the journal
        # (their union — dropping either silently breaks events_by_tag)
        for tag in ("items", "by-tagger"):
            tagged = plugin.events_by_tag(tag, 0)
            assert len(tagged) == 1, tag
            assert tagged[0][1].payload == Wrapped("apple")
    finally:
        system.terminate()
        system.await_termination(10.0)


# -- typed SnapshotAdapter ----------------------------------------------------

def test_snapshot_adapter_upcasts_old_snapshot(tmp_path):
    """Behavior A snapshots OLD-format state (a list); behavior B declares
    a SnapshotAdapter upcasting list -> dict and recovers from A's
    snapshot (typed/SnapshotAdapterSpec semantics)."""
    jdir, sdir = str(tmp_path / "j"), str(tmp_path / "s")
    pid = _plugin_id("snap")
    Persistence.register_journal_plugin(pid, lambda _s, _c: FileJournal(jdir))

    def ch_old(state, cmd):
        if isinstance(cmd, tuple) and cmd[0] == "add":
            return Effect.persist(ItemAdded(cmd[1]))
        return Effect.reply(cmd, state)

    system = _system(pid, snapshot_dir=sdir)
    try:
        ref = system.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "s1"), [], ch_old,
            lambda st, ev: st + [ev.item],
            retention=RetentionCriteria.snapshot_every_n(1),
            journal_plugin_id=pid)), "cart")
        probe = TestProbe(system)
        ref.tell(("add", "apple"))
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ["apple"]
    finally:
        system.terminate()
        system.await_termination(10.0)

    class ListToDict(SnapshotAdapter):
        def to_journal(self, state):
            return state  # store v2 states as-is

        def from_journal(self, stored):
            return {"items": list(stored)} if isinstance(stored, list) \
                else stored

    system2 = _system(pid, snapshot_dir=sdir)
    try:
        def ch_new(state, cmd):
            return Effect.reply(cmd, state)

        ref = system2.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "s1"), {"items": []}, ch_new,
            lambda st, ev: {"items": st["items"] + [ev.item]},
            journal_plugin_id=pid, snapshot_adapter=ListToDict())), "cart")
        probe = TestProbe(system2)
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == {"items": ["apple"]}
    finally:
        system2.terminate()
        system2.await_termination(10.0)


def test_typed_event_adapter_on_behavior(tmp_path):
    """Per-behavior typed EventAdapter (reference: persistence-typed
    EventAdapter.scala): write-side detachment + read-side restore applied
    by the behavior itself, without any journal-level registry."""
    d = str(tmp_path / "j")
    pid = _plugin_id("typed-ea")
    Persistence.register_journal_plugin(pid, lambda _s, _c: FileJournal(d))

    def command_handler(state, cmd):
        if isinstance(cmd, tuple) and cmd[0] == "add":
            return Effect.persist(ItemAdded(cmd[1]))
        return Effect.reply(cmd, tuple(state))

    def event_handler(state, event):
        assert isinstance(event, ItemAdded), event
        return state + [event.item]

    def spawn(system, name):
        return system.actor_of(props_from_behavior(EventSourcedBehavior(
            PersistenceId.of("Cart", "tea1"), [], command_handler,
            event_handler, journal_plugin_id=pid,
            event_adapter=WrappingAdapter())), name)

    system = _system(pid)
    try:
        ref = spawn(system, "cart")
        probe = TestProbe(system)
        ref.tell(("add", "kiwi"))
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ("kiwi",)
    finally:
        system.terminate()
        system.await_termination(10.0)

    stored = []
    FileJournal(d).replay("Cart|tea1", 1, 2**63 - 1, 2**63 - 1, stored.append)
    assert [type(r.payload) for r in stored] == [Wrapped]
    assert stored[0].manifest == "wrapped-v1"

    system2 = _system(pid)
    try:
        ref = spawn(system2, "cart")
        probe = TestProbe(system2)
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == ("kiwi",)
    finally:
        system2.terminate()
        system2.await_termination(10.0)


def test_late_adapter_registration_rejected(tmp_path):
    pid = _plugin_id("late")
    Persistence.register_journal_plugin(
        pid, lambda _s, _c: FileJournal(str(tmp_path / "j")))
    system = _system(pid)
    try:
        Persistence.get(system).journal_for(pid)  # journal now started
        with pytest.raises(RuntimeError, match="already started"):
            Persistence.get(system).register_event_adapters(
                pid, EventAdapters())
    finally:
        system.terminate()
        system.await_termination(10.0)
