"""Cluster sharding tests — modeled on the reference multi-jvm specs
(akka-cluster-sharding/src/multi-jvm: ClusterShardingSpec,
ClusterShardingRebalanceSpec, ClusterShardingRememberEntitiesSpec) and unit
specs (LeastShardAllocationStrategySpec), over the in-proc transport."""

import pytest

from akka_tpu import ActorSystem, Props
from akka_tpu.actor.actor import Actor
from akka_tpu.cluster import Cluster
from akka_tpu.sharding import (ClusterSharding, ClusterShardingSettings,
                               ClusterShardingTyped, Entity, EntityTypeKey,
                               InProcRememberEntitiesStore,
                               LeastShardAllocationStrategy, Passivate,
                               ShardingEnvelope, StartEntity, StartEntityAck)
from akka_tpu.remote.transport import InProcTransport
from akka_tpu.testkit import TestProbe, await_condition
from akka_tpu.typed import Behaviors

FAST = {"akka": {"actor": {"provider": "cluster"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}},
                 "cluster": {"gossip-interval": "0.05s",
                             "leader-actions-interval": "0.05s",
                             "unreachable-nodes-reaper-interval": "0.1s",
                             "failure-detector": {
                                 "heartbeat-interval": "0.1s",
                                 "acceptable-heartbeat-pause": "2s"}}}}

SETTINGS = ClusterShardingSettings(number_of_shards=8, retry_interval=0.1,
                                   rebalance_interval=0.3)


class Counter(Actor):
    """Per-entity counter; replies (entity_path_host, count)."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def receive(self, message):
        if message == "inc":
            self.count += 1
        elif message == "get":
            self.sender.tell((str(self.context.system.name), self.count),
                             self.self_ref)
        elif message == "passivate":
            self.context.parent.tell(Passivate(), self.self_ref)
        else:
            return NotImplemented


# -- allocation strategy unit tests ------------------------------------------

def test_least_shard_allocation():
    s = LeastShardAllocationStrategy(rebalance_threshold=1,
                                     max_simultaneous_rebalance=3)
    current = {"r1": ["a", "b", "c"], "r2": ["d"], "r3": []}
    assert s.allocate_shard("r1", "x", current) == "r3"
    moves = s.rebalance(current, set())
    assert "a" in moves  # from the most loaded region
    assert not s.rebalance({"r1": ["a"], "r2": []}, set())  # within threshold
    assert not s.rebalance(current, {"m1", "m2", "m3"})  # limit in flight


# -- single-node hosting ------------------------------------------------------

@pytest.fixture()
def one_node():
    InProcTransport.fault_injector.reset()
    InProcRememberEntitiesStore.reset()
    s = ActorSystem.create("sh0", FAST)
    c = Cluster.get(s)
    c.join(str(s.provider.local_address))
    await_condition(lambda: any(m.status.value == "Up"
                                for m in c.state.members), max_time=10.0)
    yield s
    s.terminate()
    s.await_termination(10.0)
    InProcRememberEntitiesStore.reset()


def test_entities_receive_and_keep_state(one_node):
    region = ClusterSharding.get(one_node).start(
        "counters", Props.create(Counter), SETTINGS)
    probe = TestProbe(one_node)
    for _ in range(3):
        region.tell(ShardingEnvelope("e1", "inc"), probe.ref)
    region.tell(ShardingEnvelope("e2", "inc"), probe.ref)

    def counted():
        region.tell(ShardingEnvelope("e1", "get"), probe.ref)
        try:
            return probe.receive_one(1.0)[1] == 3
        except AssertionError:
            return False
    await_condition(counted, max_time=10.0)
    region.tell(ShardingEnvelope("e2", "get"), probe.ref)
    assert probe.receive_one(5.0)[1] == 1


def test_start_entity_and_passivation(one_node):
    region = ClusterSharding.get(one_node).start(
        "counters", Props.create(Counter), SETTINGS)
    probe = TestProbe(one_node)
    region.tell(StartEntity("e9"), probe.ref)
    ack = probe.expect_msg_class(StartEntityAck, timeout=5.0)
    assert ack.entity_id == "e9"
    # passivate, then a new message restarts it with fresh state
    region.tell(ShardingEnvelope("e9", "inc"), probe.ref)
    region.tell(ShardingEnvelope("e9", "passivate"), probe.ref)

    def restarted():
        region.tell(ShardingEnvelope("e9", "get"), probe.ref)
        try:
            return probe.receive_one(1.0)[1] == 0  # state reset after stop
        except AssertionError:
            return False
    await_condition(restarted, max_time=10.0)


# -- multi-node: distribution, forwarding, rebalance --------------------------

@pytest.fixture()
def two_nodes():
    InProcTransport.fault_injector.reset()
    InProcRememberEntitiesStore.reset()
    systems = [ActorSystem.create(f"sh{i}", FAST) for i in range(2)]
    clusters = [Cluster.get(s) for s in systems]
    first = str(systems[0].provider.local_address)
    for c in clusters:
        c.join(first)
    await_condition(
        lambda: all(len([m for m in c.state.members
                         if m.status.value == "Up"]) == 2 for c in clusters),
        max_time=10.0)
    yield systems, clusters
    for s in systems:
        s.terminate()
    for s in systems:
        s.await_termination(10.0)
    InProcTransport.fault_injector.reset()
    InProcRememberEntitiesStore.reset()


def test_cross_node_forwarding_and_rebalance(two_nodes):
    systems, clusters = two_nodes
    regions = [ClusterSharding.get(s).start("counters", Props.create(Counter),
                                            SETTINGS) for s in systems]
    probe0 = TestProbe(systems[0])
    probe1 = TestProbe(systems[1])
    # drive all 8 shards from node0; rebalance should spread them
    for i in range(32):
        regions[0].tell(ShardingEnvelope(f"e{i}", "inc"), probe0.ref)

    def spread():
        hosts = set()
        for i in range(32):
            regions[1].tell(ShardingEnvelope(f"e{i}", "get"), probe1.ref)
        try:
            for _ in range(32):
                hosts.add(probe1.receive_one(2.0)[0])
        except AssertionError:
            return False
        return hosts == {"sh0", "sh1"}
    await_condition(spread, max_time=20.0)
    # the entity answers from wherever it now lives; a rebalanced entity
    # restarts fresh (state continuity needs persistence/remember-entities)
    regions[1].tell(ShardingEnvelope("e5", "get"), probe1.ref)
    assert probe1.receive_one(5.0)[1] in (0, 1)


def test_remember_entities_restart_after_rebalance(two_nodes):
    systems, _ = two_nodes
    settings = ClusterShardingSettings(number_of_shards=2, retry_interval=0.1,
                                       rebalance_interval=0.3,
                                       remember_entities=True)
    store = InProcRememberEntitiesStore()
    regions = [ClusterSharding.get(s).start("rem", Props.create(Counter),
                                            settings, store=store)
               for s in systems]
    probe = TestProbe(systems[0])
    regions[0].tell(ShardingEnvelope("r1", "inc"), probe.ref)

    def remembered():
        return any(store.remembered("rem", str(s)) == {"r1"}
                   for s in range(2))
    await_condition(remembered, max_time=10.0)


# -- typed façade -------------------------------------------------------------

def typed_counter(entity_id: str):
    def behavior(count=0):
        def on_message(ctx, msg):
            if isinstance(msg, tuple) and msg[0] == "get":
                msg[1].tell((entity_id, count))
                return Behaviors.same()
            if msg == "inc":
                return behavior(count + 1)
            return Behaviors.same()
        return Behaviors.receive(on_message)
    return behavior()


def test_typed_entity_ref(one_node):
    key = EntityTypeKey("typed-counters")
    sharding = ClusterShardingTyped.get(one_node)
    sharding.init(Entity(key, lambda ctx: typed_counter(ctx.entity_id),
                         settings=SETTINGS))
    ref = sharding.entity_ref_for(key, "alice")
    probe = TestProbe(one_node)
    ref.tell("inc")
    ref.tell("inc")

    def counted():
        ref.tell(("get", probe.ref))
        try:
            return probe.receive_one(1.0) == ("alice", 2)
        except AssertionError:
            return False
    await_condition(counted, max_time=10.0)
