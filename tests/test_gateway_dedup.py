"""Journaled reply-cache dedup (akka_tpu/gateway/dedup.py) — the server
half of exactly-once effects (ISSUE 20).

Tier-1 scope: ReplyCacheTable unit contracts (window eviction,
LRU spill + bit-exact rehydrate, pending/inflight, journal-order load)
plus cheap in-proc gateway legs on the virtual CPU mesh: duplicate
retries replay the cached reply on BOTH encodings without re-applying,
evicted ids re-apply (the documented at-least-once degradation), and
idempotent client sessions mint stable ids. The kill -9 + restore
rehydration legs live in tests/test_gateway_chaos.py (slow tier)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from akka_tpu.gateway import (AdmissionController, GatewayClient,
                              GatewayServer, RegionBackend, ReplyCacheTable,
                              SloTracker, counter_behavior)
from akka_tpu.gateway.dedup import DUPLICATE_INFLIGHT
from akka_tpu.gateway.ingress import encode_body
from akka_tpu.serialization import frames


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------- table contracts
def test_reply_cache_miss_record_hit_roundtrip():
    dd = ReplyCacheTable(window=16)
    key = ("t0", 101)
    (v,) = dd.begin([key])
    assert v == ("miss",)
    dd.record(key, frames.ST_OK, 7.5)
    (v,) = dd.begin([key])
    assert v == ("hit", frames.ST_OK, 7.5, b"")
    st = dd.stats()
    assert st["hits"] == 1 and st["misses"] == 1 and st["records"] == 1


def test_reply_cache_release_lets_retry_run_fresh():
    dd = ReplyCacheTable()
    key = ("t0", 1)
    assert dd.begin([key]) == [("miss",)]
    dd.release(key)  # shed/fault: nothing applied, nothing cached
    assert dd.begin([key]) == [("miss",)]
    assert dd.lookup(key) is None


def test_reply_cache_same_window_alias_and_inflight():
    dd = ReplyCacheTable()
    a, b = ("t0", 5), ("t0", 6)
    # duplicate INSIDE one window aliases its source row, not a shed
    out = dd.begin([a, b, a, None])
    assert out == [("miss",), ("miss",), ("alias", 0), ("skip",)]
    # duplicate ACROSS windows while the first attempt is still pending
    # is the typed inflight shed — never a second application
    assert dd.begin([a]) == [("inflight",)]
    assert dd.stats()["inflight_sheds"] == 1
    assert DUPLICATE_INFLIGHT == "duplicate_inflight"


def test_reply_cache_pending_ttl_expiry_degrades_to_miss():
    clk = FakeClock()
    dd = ReplyCacheTable(pending_ttl_s=30.0, clock=clk)
    key = ("t0", 9)
    assert dd.begin([key]) == [("miss",)]
    clk.advance(10.0)
    assert dd.begin([key]) == [("inflight",)]
    clk.advance(31.0)  # a crashed serve path leaked the pending mark
    assert dd.begin([key]) == [("miss",)]
    assert dd.stats()["pending_expired"] == 1


def test_reply_cache_window_eviction_is_per_tenant():
    dd = ReplyCacheTable(window=2)
    for rid in (1, 2, 3):
        dd.record(("t0", rid), frames.ST_OK, float(rid))
    dd.record(("t1", 1), frames.ST_OK, 9.0)
    # t0's oldest id fell off the 2-id window: FORGOTTEN entirely
    assert dd.lookup(("t0", 1)) is None
    assert dd.begin([("t0", 1)]) == [("miss",)]
    dd.release(("t0", 1))
    # the newer two ids and the OTHER tenant's frontier are untouched
    assert dd.lookup(("t0", 2)) == (frames.ST_OK, 2.0, b"")
    assert dd.lookup(("t0", 3)) == (frames.ST_OK, 3.0, b"")
    assert dd.lookup(("t1", 1)) == (frames.ST_OK, 9.0, b"")
    assert dd.stats()["window_evictions"] == 1


def test_reply_cache_spill_rehydrate_bit_identical():
    dd = ReplyCacheTable(window=64, max_resident=2, init_capacity=2)
    v1 = 0.1 + 0.2  # a value whose f64 bits are easy to get wrong
    dd.record(("t0", 1), frames.ST_OK, v1)
    dd.record(("t0", 2), frames.ST_ERROR, 0.0, b"timeout")
    dd.record(("t0", 3), frames.ST_OK, 3.25)  # LRU-spills ("t0", 1)
    st = dd.stats()
    assert st["spills"] == 1 and st["resident"] == 2 and st["spilled"] == 1
    # spilled rows keep RAW scalars: the point probe and the begin()
    # rehydrate must both return the exact f64 bit pattern
    got = dd.lookup(("t0", 1))
    assert got is not None
    assert np.float64(got[1]).tobytes() == np.float64(v1).tobytes()
    (v,) = dd.begin([("t0", 1)])
    assert v[0] == "hit"
    assert np.float64(v[2]).tobytes() == np.float64(v1).tobytes()
    assert dd.stats()["rehydrates"] == 1
    # error replies rehydrate their interned reason too
    dd.record(("t0", 4), frames.ST_OK, 4.0)  # spills another row
    assert dd.lookup(("t0", 2)) == (frames.ST_ERROR, 0.0, b"timeout")


def test_reply_cache_load_applies_window_in_journal_order():
    dd = ReplyCacheTable(window=2)
    n = dd.load([("t0", i, frames.ST_OK, float(i)) for i in (1, 2, 3)])
    assert n == 3
    st = dd.stats()
    assert st["loads"] == 3 and st["records"] == 0  # loads are not live
    # journal longer than the window keeps only the NEWEST window ids —
    # exactly the frontier the live path would have kept
    assert dd.lookup(("t0", 1)) is None
    assert dd.lookup(("t0", 2)) == (frames.ST_OK, 2.0, b"")
    assert dd.lookup(("t0", 3)) == (frames.ST_OK, 3.0, b"")


# -------------------------------------------------- idempotent sessions
def test_client_session_ids_are_stable_and_positive():
    c = GatewayClient("127.0.0.1", 1, session=0xDEADBEEFCAFE)
    ids = [c._next_id() for _ in range(3)]
    assert all(i > 0 for i in ids)
    # (session << 24) | seq, masked positive: consecutive ids differ
    # only in the 24-bit seq, the session tag is stable
    assert [i & 0xFFFFFF for i in ids] == [1, 2, 3]
    assert len({i >> 24 for i in ids}) == 1
    assert ids[0] >> 24 == (0xDEADBEEFCAFE << 24 & 0x7FFFFFFFFFFFFFFF) >> 24
    # two clients NEVER share a session tag by construction
    c2 = GatewayClient("127.0.0.1", 1, session=0xDEADBEEFCAFF)
    assert c2._next_id() != ids[0]


# ------------------------------------------------- in-proc gateway legs
@pytest.fixture(scope="module")
def small_region():
    from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion
    spec = DeviceEntity("gwd", counter_behavior(4), n_shards=2,
                        entities_per_shard=8, n_devices=2, payload_width=4)
    return DeviceShardRegion(spec)


def _server(region, dedup):
    return GatewayServer(None, RegionBackend(region),
                         AdmissionController(rate=1e6, burst=1e6),
                         SloTracker(), dedup=dedup)


def _req(server, tenant, entity, op, value=0.0, rid=1):
    body = encode_body({"id": rid, "tenant": tenant, "entity": entity,
                        "op": op, "value": value})
    return json.loads(server.handle_frame(body))


def test_gateway_duplicate_retry_replays_without_reapply(small_region):
    srv = _server(small_region, ReplyCacheTable())
    first = _req(srv, "t0", "acct-0", "add", 5.0, rid=77)
    assert first["status"] == "ok" and "dedup" not in first
    replay = _req(srv, "t0", "acct-0", "add", 5.0, rid=77)
    # identical reply content, marked as a replay, effect applied ONCE
    assert replay["dedup"] is True
    assert (replay["id"], replay["status"], replay["value"]) == \
        (first["id"], first["status"], first["value"])
    assert _req(srv, "t0", "acct-0", "get", rid=78)["value"] == \
        pytest.approx(first["value"])
    st = srv.dedup.stats()
    assert st["hits"] == 1 and st["records"] >= 2


def test_gateway_same_window_duplicate_both_encodings(small_region):
    # BINARY: two records with the SAME id inside ONE 0xAB window — the
    # alias row copies its source row's resolved reply
    srv = _server(small_region, ReplyCacheTable())
    body = frames.encode_request_batch(
        [501, 501], ["t0", "t0"], ["acct-1", "acct-1"],
        ["add", "add"], [4.0, 4.0])
    reps = frames.decode_replies(srv.handle_frame(body))
    assert reps[0]["status"] == "ok" and "dedup" not in reps[0]
    assert reps[1]["dedup"] is True
    assert (reps[1]["id"], reps[1]["status"], reps[1]["value"]) == \
        (reps[0]["id"], reps[0]["status"], reps[0]["value"])
    assert srv.dedup.stats()["alias_hits"] == 1
    # applied once: the counter saw ONE add
    assert _req(srv, "t0", "acct-1", "get", rid=502)["value"] == \
        pytest.approx(reps[0]["value"])
    # JSON path against the SAME cache: a cross-encoding retry of the
    # binary-minted id replays the identical reply content
    rep = _req(srv, "t0", "acct-1", "add", 4.0, rid=501)
    assert rep["dedup"] is True and rep["value"] == reps[0]["value"]


def test_gateway_evicted_id_reapplies_at_least_once(small_region):
    # window=1: recording id B forgets id A; a retry of A re-applies —
    # the documented per-tenant at-least-once degradation
    srv = _server(small_region, ReplyCacheTable(window=1))
    a = _req(srv, "t0", "acct-2", "add", 2.0, rid=601)
    assert a["status"] == "ok"
    b = _req(srv, "t0", "acct-2", "add", 3.0, rid=602)
    assert b["status"] == "ok" and b["value"] == a["value"] + 3.0
    retry_a = _req(srv, "t0", "acct-2", "add", 2.0, rid=601)
    assert retry_a["status"] == "ok" and "dedup" not in retry_a
    assert retry_a["value"] == pytest.approx(b["value"] + 2.0)
    assert srv.dedup.stats()["window_evictions"] >= 1


def test_gateway_dedup_is_post_admission(small_region):
    # a duplicate of a cached id still pays the admission charge: a
    # zero-budget tenant's retry sheds, it does NOT get a cached reply
    dd = ReplyCacheTable()
    srv = GatewayServer(None, RegionBackend(small_region),
                        AdmissionController(rate=0.001, burst=1.0),
                        SloTracker(), dedup=dd)
    first = _req(srv, "t9", "acct-3", "add", 1.0, rid=701)
    assert first["status"] == "ok"
    retry = _req(srv, "t9", "acct-3", "add", 1.0, rid=701)
    assert retry["status"] == "shed" and "dedup" not in retry
    assert dd.stats()["hits"] == 0
