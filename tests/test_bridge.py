"""The tpu-batched dispatcher bridge: ActorRef.tell -> device rows (VERDICT
r1 item 2).

Covers the reference seam being replaced: Dispatchers type selection
(dispatch/Dispatchers.scala:121-259), the tell hot path (SURVEY.md §3.2) and
ask via promise refs (pattern/AskSupport.scala:476) — all against the device
runtime through the PUBLIC ActorSystem API.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from akka_tpu import ActorSystem
from akka_tpu.batched import (DeviceActorRef, DeviceBlockRef, Emit, Mailbox,
                              behavior, device_props, get_handle, reply_dst)
from akka_tpu.pattern.ask import ask_sync

F32, I32 = jnp.float32, jnp.int32

ADD, GET = 0, 1

CFG = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                "actor": {"tpu-dispatcher": {
                    "capacity": 1 << 12, "payload-width": 4,
                    "mailbox-slots": 4, "host-inbox": 8192,
                    "promise-rows": 32}}}}


@behavior("counter", {"count": ((), F32)}, inbox="slots")
def counter(state, mailbox: Mailbox, ctx):
    def apply(carry, t, pl):
        cnt, rdst = carry
        return (jnp.where(t == ADD, cnt + pl[0], cnt),
                jnp.where(t == GET, reply_dst(pl), rdst))

    cnt, rdst = mailbox.fold((state["count"], jnp.asarray(-1, I32)), apply)
    return ({"count": cnt},
            Emit.single(rdst, cnt, 1, 4, when=rdst >= 0))


def make_system(name):
    return ActorSystem.create(name, CFG)


def test_device_actor_tell_and_read():
    system = make_system("bridge-tell")
    try:
        ref = system.actor_of(device_props(counter), "c1")
        assert isinstance(ref, DeviceActorRef)
        assert ref.path.name == "c1"
        for x in (1.0, 2.0, 3.5):
            ref.tell((ADD, [x]))
        h = get_handle(system)
        h.step()
        assert ref.read_state("count") == 6.5
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_ask_roundtrip():
    """ask completes via a promise row the behavior replies to — the
    device-resident PromiseActorRef."""
    system = make_system("bridge-ask")
    try:
        ref = system.actor_of(device_props(counter), "c2")
        ref.tell((ADD, [10.0]))
        ref.tell((ADD, [5.0]))
        # the auto-pump drives steps; no manual stepping
        reply = ask_sync(ref, (GET, [0.0]), timeout=10.0)
        assert reply[0] == 15.0
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_ping_pong_public_api():
    """BASELINE TellOnly/ping-pong shape through system.actor_of: two device
    actors exchanging a counter token."""

    @behavior("pp", {"hits": ((), F32), "peer": ((), I32)}, inbox="slots")
    def pp(state, mailbox: Mailbox, ctx):
        def apply(carry, t, pl):
            return carry + pl[0]

        got = mailbox.fold(jnp.asarray(0.0, F32), apply)
        any_msg = mailbox.count > 0
        return ({"hits": state["hits"] + got},
                Emit.single(state["peer"], jnp.asarray([1.0]), 1, 4,
                            when=any_msg))

    system = make_system("bridge-pp")
    try:
        a = system.actor_of(device_props(pp), "a")
        b = system.actor_of(
            device_props(pp, init_state={"peer": np.asarray([0], np.int32)}),
            "b")
        h = get_handle(system)
        # wire a -> b after spawn (rows are known now)
        h.runtime.state["peer"] = h.runtime.state["peer"].at[a.row].set(b.row)
        a.tell((0, [1.0]))     # serve
        h.step(20)             # 20 steps of volleys on device
        total = float(a.read_state("hits") + b.read_state("hits"))
        assert total >= 19.0   # one hop per step after the serve lands
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_block_ring_public_api():
    """BASELINE ring config through the public API: one block ref, bulk
    seed, on-device volleys, no per-actor Python objects."""

    @behavior("ringb", {"received": ((), F32)}, inbox="slots")
    def ringb(state, mailbox: Mailbox, ctx):
        def apply(carry, t, pl):
            return carry + pl[0]

        got = mailbox.fold(jnp.asarray(0.0, F32), apply)
        nxt = (ctx.actor_id + 1) % jnp.asarray(256, I32)
        return ({"received": state["received"] + got},
                Emit.single(nxt, jnp.asarray([1.0]), 1, 4,
                            when=mailbox.count > 0))

    system = make_system("bridge-ring")
    try:
        block = system.actor_of(device_props(ringb, n=256), "ring")
        assert isinstance(block, DeviceBlockRef)
        assert len(block) == 256
        block.tell((0, [1.0]))  # one token to every actor (bulk staged)
        h = get_handle(system)
        h.step(10)
        # every executed step delivers exactly one token per actor; the
        # auto-pump may step at ANY point between these reads, so snapshot
        # the authoritative device step counter FIRST and lower-bound the
        # delivered total (reading received first raced a pump slipping in
        # between the two reads — observed once in a full-suite run)
        import jax
        steps_before = int(jax.device_get(h.runtime.step_count))
        received = block.read_state("received")
        assert steps_before >= 10
        assert received.sum() >= 256 * steps_before
        assert received.sum() % 256 == 0
        # single-row ref derived from the block works
        r0 = block[0]
        assert isinstance(r0, DeviceActorRef)
        assert r0.read_state("received") == received[0]
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_rebuild_on_new_behavior_preserves_state():
    """Spawning a new behavior type after the runtime is built re-traces the
    switch while keeping rows, state and pending messages."""
    system = make_system("bridge-rebuild")
    try:
        c = system.actor_of(device_props(counter), "c")
        c.tell((ADD, [7.0]))
        h = get_handle(system)
        h.step()
        assert c.read_state("count") == 7.0

        @behavior("other", {"seen": ((), F32)}, inbox="slots")
        def other(state, mailbox: Mailbox, ctx):
            def apply(carry, t, pl):
                return carry + pl[0]
            return ({"seen": state["seen"] +
                     mailbox.fold(jnp.asarray(0.0, F32), apply)},
                    Emit.none(1, 4))

        o = system.actor_of(device_props(other), "o")
        c.tell((ADD, [3.0]))
        o.tell((0, [2.0]))
        h.step()
        assert c.read_state("count") == 10.0  # old state survived rebuild
        assert o.read_state("seen") == 2.0
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_ref_watch_and_stop_dead_letters():
    from akka_tpu.actor.messages import DeadLetter
    from akka_tpu.testkit import TestProbe
    system = make_system("bridge-watch")
    try:
        ref = system.actor_of(device_props(counter), "mortal")
        probe = TestProbe(system)
        probe.watch(ref)
        dl_probe = TestProbe(system)
        system.event_stream.subscribe(dl_probe.ref, DeadLetter)
        ref.stop()
        t = probe.expect_terminated(ref, 5.0)
        assert t.actor is ref
        ref.tell((ADD, [1.0]))  # late tell -> dead letters
        dl = dl_probe.receive_one(5.0)
        assert isinstance(dl, DeadLetter)
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_default_dispatcher_tpu_batched():
    """The north star seam: akka.actor.default-dispatcher.type=tpu-batched —
    host actors still run (they share the dispatcher thread pool), device
    props land on the device, through the same public API."""
    cfg = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "actor": {"default-dispatcher": {
                        "type": "tpu-batched",
                        "capacity": 1 << 10, "payload-width": 4,
                        "mailbox-slots": 4, "promise-rows": 16,
                        "host-inbox": 1024}}}}
    system = ActorSystem.create("bridge-default", cfg)
    try:
        # a plain host actor on the tpu-batched dispatcher's thread pool
        from akka_tpu import Props
        from akka_tpu.actor.actor import Actor
        from akka_tpu.testkit import TestProbe

        class Echo(Actor):
            def receive(self, message):
                self.sender.tell(("echo", message), self.self_ref)

        host = system.actor_of(Props(factory=Echo, cls=Echo), "host-echo")
        probe = TestProbe(system)
        host.tell("hi", probe.ref)
        assert probe.receive_one(5.0) == ("echo", "hi")

        # a device actor through the same default dispatcher
        dev = system.actor_of(device_props(counter), "dev-counter")
        dev.tell((ADD, [4.0]))
        assert ask_sync(dev, (GET, [0.0]), timeout=10.0)[0] == 4.0
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_ask_reply_id_dtype_validated_at_build():
    """VERDICT r3 #6: the ask reply-to row id is a value cast into the
    payload dtype's last column; a capacity whose ids cannot roundtrip
    must fail FAST at handle construction, not corrupt routing silently
    (AskSupport.scala:476 — PromiseActorRef identity is never lossy)."""
    import jax.numpy as jnp
    import pytest
    from akka_tpu.batched.bridge import BatchedRuntimeHandle, max_exact_row_id

    # float32: 2^24 ids are exact -> 1M rows fine
    BatchedRuntimeHandle(capacity=1 << 20, payload_dtype=jnp.float32)
    # bfloat16: only 2^8 ids are exact -> 1M rows must be refused
    with pytest.raises(ValueError, match="bfloat16"):
        BatchedRuntimeHandle(capacity=1 << 20, payload_dtype=jnp.bfloat16)
    # ...but a system small enough for bf16 ids builds
    BatchedRuntimeHandle(capacity=256, payload_dtype=jnp.bfloat16,
                         promise_rows=8)
    # float16: 2^11
    with pytest.raises(ValueError, match="float16"):
        BatchedRuntimeHandle(capacity=1 << 12, payload_dtype=jnp.float16)
    assert max_exact_row_id(jnp.float32) == 1 << 24
    assert max_exact_row_id(jnp.bfloat16) == 1 << 8
    assert max_exact_row_id(jnp.int32) == (1 << 31) - 1


def test_bf16_small_system_ask_roundtrip():
    """A bf16 payload system within the exact-id range must WORK end to
    end: ask routes the reply through the value-cast id correctly."""
    import jax.numpy as jnp
    from akka_tpu.batched import Emit, behavior
    from akka_tpu.batched.bridge import BatchedRuntimeHandle, reply_dst

    P = 4

    @behavior("bf16-echo", {})
    def echo(state, inbox, ctx):
        return (state, Emit.single(
            reply_dst(inbox.sum), inbox.sum * 2, 1, P,
            when=inbox.count > 0))

    h = BatchedRuntimeHandle(capacity=128, payload_width=P,
                             payload_dtype=jnp.bfloat16, promise_rows=8,
                             host_inbox=32)
    try:
        rows = h.spawn(echo, 1)
        fut = h.ask(int(rows[0]), (0, [3.0, 0, 0, 0]), timeout=30.0)
        reply = fut.result(40.0)
        assert float(reply[0]) == 6.0
    finally:
        h.shutdown()
