"""The tpu-batched dispatcher bridge: ActorRef.tell -> device rows (VERDICT
r1 item 2).

Covers the reference seam being replaced: Dispatchers type selection
(dispatch/Dispatchers.scala:121-259), the tell hot path (SURVEY.md §3.2) and
ask via promise refs (pattern/AskSupport.scala:476) — all against the device
runtime through the PUBLIC ActorSystem API.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from akka_tpu import ActorSystem
from akka_tpu.batched import (DeviceActorRef, DeviceBlockRef, Emit, Mailbox,
                              behavior, device_props, get_handle, reply_dst)
from akka_tpu.pattern.ask import ask_sync

F32, I32 = jnp.float32, jnp.int32

ADD, GET = 0, 1

CFG = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                "actor": {"tpu-dispatcher": {
                    "capacity": 1 << 12, "payload-width": 4,
                    "mailbox-slots": 4, "host-inbox": 8192,
                    "promise-rows": 32}}}}


@behavior("counter", {"count": ((), F32)}, inbox="slots")
def counter(state, mailbox: Mailbox, ctx):
    def apply(carry, t, pl):
        cnt, rdst = carry
        return (jnp.where(t == ADD, cnt + pl[0], cnt),
                jnp.where(t == GET, reply_dst(pl), rdst))

    cnt, rdst = mailbox.fold((state["count"], jnp.asarray(-1, I32)), apply)
    return ({"count": cnt},
            Emit.single(rdst, cnt, 1, 4, when=rdst >= 0))


def make_system(name):
    return ActorSystem.create(name, CFG)


def test_device_actor_tell_and_read():
    system = make_system("bridge-tell")
    try:
        ref = system.actor_of(device_props(counter), "c1")
        assert isinstance(ref, DeviceActorRef)
        assert ref.path.name == "c1"
        for x in (1.0, 2.0, 3.5):
            ref.tell((ADD, [x]))
        h = get_handle(system)
        h.step()
        assert ref.read_state("count") == 6.5
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_ask_roundtrip():
    """ask completes via a promise row the behavior replies to — the
    device-resident PromiseActorRef."""
    system = make_system("bridge-ask")
    try:
        ref = system.actor_of(device_props(counter), "c2")
        ref.tell((ADD, [10.0]))
        ref.tell((ADD, [5.0]))
        # the auto-pump drives steps; no manual stepping
        reply = ask_sync(ref, (GET, [0.0]), timeout=10.0)
        assert reply[0] == 15.0
    finally:
        system.terminate()
        system.await_termination(10.0)


@pytest.mark.slow  # ~13 s: demoted to the slow tier (ISSUE 18 budget
# note) — multi-actor device emit volleys through the public API stay
# tier-1-covered by test_device_block_ring_public_api
def test_device_ping_pong_public_api():
    """BASELINE TellOnly/ping-pong shape through system.actor_of: two device
    actors exchanging a counter token."""

    @behavior("pp", {"hits": ((), F32), "peer": ((), I32)}, inbox="slots")
    def pp(state, mailbox: Mailbox, ctx):
        def apply(carry, t, pl):
            return carry + pl[0]

        got = mailbox.fold(jnp.asarray(0.0, F32), apply)
        any_msg = mailbox.count > 0
        return ({"hits": state["hits"] + got},
                Emit.single(state["peer"], jnp.asarray([1.0]), 1, 4,
                            when=any_msg))

    system = make_system("bridge-pp")
    try:
        a = system.actor_of(device_props(pp), "a")
        b = system.actor_of(
            device_props(pp, init_state={"peer": np.asarray([0], np.int32)}),
            "b")
        h = get_handle(system)
        # wire a -> b after spawn (rows are known now)
        h.runtime.state["peer"] = h.runtime.state["peer"].at[a.row].set(b.row)
        a.tell((0, [1.0]))     # serve
        h.step(20)             # 20 steps of volleys on device
        total = float(a.read_state("hits") + b.read_state("hits"))
        assert total >= 19.0   # one hop per step after the serve lands
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_block_ring_public_api():
    """BASELINE ring config through the public API: one block ref, bulk
    seed, on-device volleys, no per-actor Python objects."""

    @behavior("ringb", {"received": ((), F32)}, inbox="slots")
    def ringb(state, mailbox: Mailbox, ctx):
        def apply(carry, t, pl):
            return carry + pl[0]

        got = mailbox.fold(jnp.asarray(0.0, F32), apply)
        nxt = (ctx.actor_id + 1) % jnp.asarray(256, I32)
        return ({"received": state["received"] + got},
                Emit.single(nxt, jnp.asarray([1.0]), 1, 4,
                            when=mailbox.count > 0))

    system = make_system("bridge-ring")
    try:
        block = system.actor_of(device_props(ringb, n=256), "ring")
        assert isinstance(block, DeviceBlockRef)
        assert len(block) == 256
        block.tell((0, [1.0]))  # one token to every actor (bulk staged)
        h = get_handle(system)
        h.step(10)
        # every executed step delivers exactly one token per actor; the
        # auto-pump may step at ANY point between these reads, so snapshot
        # the authoritative device step counter FIRST and lower-bound the
        # delivered total (reading received first raced a pump slipping in
        # between the two reads — observed once in a full-suite run)
        import jax
        steps_before = int(jax.device_get(h.runtime.step_count))
        received = block.read_state("received")
        assert steps_before >= 10
        assert received.sum() >= 256 * steps_before
        assert received.sum() % 256 == 0
        # single-row ref derived from the block works
        r0 = block[0]
        assert isinstance(r0, DeviceActorRef)
        assert r0.read_state("received") == received[0]
    finally:
        system.terminate()
        system.await_termination(10.0)


@pytest.mark.slow  # ~15 s: demoted to the slow tier (ISSUE 18 budget
# note) to pay for the evloop/columnar-admission tier-1 additions
def test_rebuild_on_new_behavior_preserves_state():
    """Spawning a new behavior type after the runtime is built re-traces the
    switch while keeping rows, state and pending messages."""
    system = make_system("bridge-rebuild")
    try:
        c = system.actor_of(device_props(counter), "c")
        c.tell((ADD, [7.0]))
        h = get_handle(system)
        h.step()
        assert c.read_state("count") == 7.0

        @behavior("other", {"seen": ((), F32)}, inbox="slots")
        def other(state, mailbox: Mailbox, ctx):
            def apply(carry, t, pl):
                return carry + pl[0]
            return ({"seen": state["seen"] +
                     mailbox.fold(jnp.asarray(0.0, F32), apply)},
                    Emit.none(1, 4))

        o = system.actor_of(device_props(other), "o")
        c.tell((ADD, [3.0]))
        o.tell((0, [2.0]))
        h.step()
        assert c.read_state("count") == 10.0  # old state survived rebuild
        assert o.read_state("seen") == 2.0
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_device_ref_watch_and_stop_dead_letters():
    from akka_tpu.actor.messages import DeadLetter
    from akka_tpu.testkit import TestProbe
    system = make_system("bridge-watch")
    try:
        ref = system.actor_of(device_props(counter), "mortal")
        probe = TestProbe(system)
        probe.watch(ref)
        dl_probe = TestProbe(system)
        system.event_stream.subscribe(dl_probe.ref, DeadLetter)
        ref.stop()
        t = probe.expect_terminated(ref, 5.0)
        assert t.actor is ref
        ref.tell((ADD, [1.0]))  # late tell -> dead letters
        dl = dl_probe.receive_one(5.0)
        assert isinstance(dl, DeadLetter)
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_default_dispatcher_tpu_batched():
    """The north star seam: akka.actor.default-dispatcher.type=tpu-batched —
    host actors still run (they share the dispatcher thread pool), device
    props land on the device, through the same public API."""
    cfg = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "actor": {"default-dispatcher": {
                        "type": "tpu-batched",
                        "capacity": 1 << 10, "payload-width": 4,
                        "mailbox-slots": 4, "promise-rows": 16,
                        "host-inbox": 1024}}}}
    system = ActorSystem.create("bridge-default", cfg)
    try:
        # a plain host actor on the tpu-batched dispatcher's thread pool
        from akka_tpu import Props
        from akka_tpu.actor.actor import Actor
        from akka_tpu.testkit import TestProbe

        class Echo(Actor):
            def receive(self, message):
                self.sender.tell(("echo", message), self.self_ref)

        host = system.actor_of(Props(factory=Echo, cls=Echo), "host-echo")
        probe = TestProbe(system)
        host.tell("hi", probe.ref)
        assert probe.receive_one(5.0) == ("echo", "hi")

        # a device actor through the same default dispatcher
        dev = system.actor_of(device_props(counter), "dev-counter")
        dev.tell((ADD, [4.0]))
        assert ask_sync(dev, (GET, [0.0]), timeout=10.0)[0] == 4.0
    finally:
        system.terminate()
        system.await_termination(10.0)


def test_ask_reply_id_dtype_validated_at_build():
    """VERDICT r3 #6: the ask reply-to row id is a value cast into the
    payload dtype's last column; a capacity whose ids cannot roundtrip
    must fail FAST at handle construction, not corrupt routing silently
    (AskSupport.scala:476 — PromiseActorRef identity is never lossy)."""
    import jax.numpy as jnp
    import pytest
    from akka_tpu.batched.bridge import BatchedRuntimeHandle, max_exact_row_id

    # float32: 2^24 ids are exact -> 1M rows fine
    BatchedRuntimeHandle(capacity=1 << 20, payload_dtype=jnp.float32)
    # bfloat16: only 2^8 ids are exact -> 1M rows must be refused
    with pytest.raises(ValueError, match="bfloat16"):
        BatchedRuntimeHandle(capacity=1 << 20, payload_dtype=jnp.bfloat16)
    # ...but a system small enough for bf16 ids builds
    BatchedRuntimeHandle(capacity=256, payload_dtype=jnp.bfloat16,
                         promise_rows=8)
    # float16: 2^11
    with pytest.raises(ValueError, match="float16"):
        BatchedRuntimeHandle(capacity=1 << 12, payload_dtype=jnp.float16)
    assert max_exact_row_id(jnp.float32) == 1 << 24
    assert max_exact_row_id(jnp.bfloat16) == 1 << 8
    assert max_exact_row_id(jnp.int32) == (1 << 31) - 1


def test_bf16_small_system_ask_roundtrip():
    """A bf16 payload system within the exact-id range must WORK end to
    end: ask routes the reply through the value-cast id correctly."""
    import jax.numpy as jnp
    from akka_tpu.batched import Emit, behavior
    from akka_tpu.batched.bridge import BatchedRuntimeHandle, reply_dst

    P = 4

    @behavior("bf16-echo", {})
    def echo(state, inbox, ctx):
        return (state, Emit.single(
            reply_dst(inbox.sum), inbox.sum * 2, 1, P,
            when=inbox.count > 0))

    h = BatchedRuntimeHandle(capacity=128, payload_width=P,
                             payload_dtype=jnp.bfloat16, promise_rows=8,
                             host_inbox=32)
    try:
        rows = h.spawn(echo, 1)
        fut = h.ask(int(rows[0]), (0, [3.0, 0, 0, 0]), timeout=30.0)
        reply = fut.result(40.0)
        assert float(reply[0]) == 6.0
    finally:
        h.shutdown()


# ------------------------------------------------- depth-k pipeline seams
def test_ask_timeout_with_pipeline_in_flight():
    """An ask that times out while the depth-4 pump keeps k programs in
    flight must fail with AskTimeoutException (host deadline sweep runs
    off the attention word, no wide readback needed), quarantine the
    promise row as a zombie, and leave the handle healthy: a later ask
    against a newly spawned behavior (forcing a rebuild on top of the
    zombie) still completes."""
    from akka_tpu.batched import Emit, behavior
    from akka_tpu.batched.bridge import BatchedRuntimeHandle, reply_dst
    from akka_tpu.pattern.ask import AskTimeoutException

    @behavior("mute", {})
    def mute(state, inbox, ctx):
        return state, Emit.none(1, 4)

    @behavior("echo2", {})
    def echo2(state, inbox, ctx):
        return state, Emit.single(reply_dst(inbox.sum), inbox.sum * 2, 1, 4,
                                  when=inbox.count > 0)

    h = BatchedRuntimeHandle(capacity=128, payload_width=4, promise_rows=8,
                             host_inbox=32, pipeline_depth=4)
    try:
        rows = h.spawn(mute, 1)
        fut = h.ask(int(rows[0]), (0, [1.0]), timeout=0.25)
        with pytest.raises(AskTimeoutException):
            fut.result(20.0)
        assert h._promise_zombies  # row quarantined, not recycled yet
        assert h.pipeline_stats()["steps"] > 0

        erow = h.spawn(echo2, 1)  # rebuild with the zombie outstanding
        reply = h.ask_sync(int(erow[0]), (0, [21.0]), timeout=30.0)
        assert float(reply[0]) == 42.0
    finally:
        h.shutdown()


def test_rebuild_races_full_pipeline():
    """spawn() of a new behavior (=> _rebuild_locked) racing a stepper
    thread that keeps the depth-4 pipeline full: no exceptions on either
    side, always-on rows keep advancing in lockstep, and a tell to the
    freshly spawned behavior lands exactly once."""
    import threading
    import time

    from akka_tpu.batched import Emit, behavior
    from akka_tpu.batched.bridge import BatchedRuntimeHandle

    @behavior("race-acc", {"acc": ((), F32)}, always_on=True)
    def race_acc(state, inbox, ctx):
        return {"acc": state["acc"] + 1.0}, Emit.none(1, 4)

    @behavior("race-late", {"seen": ((), F32)})
    def race_late(state, inbox, ctx):
        return ({"seen": state["seen"] + inbox.sum[0]}, Emit.none(1, 4))

    h = BatchedRuntimeHandle(capacity=128, payload_width=4, promise_rows=8,
                             host_inbox=64, pipeline_depth=4)
    errors = []
    try:
        rows = h.spawn(race_acc, 16)
        stop = threading.Event()

        def stepper():
            try:
                while not stop.is_set():
                    h.step(8)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        t = threading.Thread(target=stepper)
        t.start()
        try:
            time.sleep(0.05)  # pipeline warm and full
            lrow = h.spawn(race_late, 1)   # rebuild mid-flight
            h.tell(int(lrow[0]), (0, [5.0]))
            time.sleep(0.05)
        finally:
            stop.set()
            t.join(60.0)
        assert not t.is_alive()
        assert not errors, errors
        h.step(2)  # make sure the tell's flush has executed
        acc = np.asarray(h.read_state("acc", rows))
        assert np.unique(acc).size == 1  # lanes advanced in lockstep
        assert acc[0] >= 8.0             # ...through rebuild, not reset
        assert float(h.read_state("seen", lrow)[0]) == 5.0
    finally:
        h.shutdown()


def _chaos_parity_run(depth, backend, seed, rate, n, windows):
    """One handle lifecycle: always-on chaos accumulator + staged tells,
    driven ONLY via h.step() windows (tells go through runtime.tell so
    the background pump stays dormant and the step count is exact)."""
    import jax

    from akka_tpu.actor.supervision import Directive
    from akka_tpu.batched import Emit, LaneSupervisor, behavior
    from akka_tpu.batched.bridge import BatchedRuntimeHandle
    from akka_tpu.testkit import chaos

    @behavior("par-acc", {"acc": ((), F32)}, always_on=True,
              supervisor=LaneSupervisor(directive=Directive.RESUME))
    def par_acc(state, inbox, ctx):
        return {"acc": state["acc"] + 1.0 + inbox.sum[0]}, Emit.none(1, 4)

    b = chaos.inject(par_acc, seed=seed, crash_rate=rate)
    h = BatchedRuntimeHandle(capacity=128, payload_width=4, promise_rows=8,
                             host_inbox=64, pipeline_depth=depth,
                             delivery_backend=backend)
    try:
        rows = h.spawn(b, n)
        base = int(rows[0])
        msg = 0
        for w in windows:
            # deterministic tell schedule exercising the delivery backend
            for _ in range(3):
                h.runtime.tell(base + (msg % n), [float(msg + 1), 0, 0, 0])
                msg += 1
            h.step(w)
        rt = h.runtime
        state = {k: np.asarray(jax.device_get(v))
                 for k, v in sorted(rt.state.items())}
        counts = dict(rt.supervision_counts)
        steps = int(jax.device_get(rt.step_count))
        return np.asarray(rows), state, counts, steps
    finally:
        h.shutdown()


@pytest.mark.parametrize("backend", ["xla", "reference"])
def test_depth_k_bit_parity_with_chaos_oracle(backend):
    """Depth-1 (synchronous pump) and depth-4 (pipelined) runs of the
    same chaos schedule must be BIT-identical: every state column, the
    supervision counters and the step count. The failed counter is also
    checked against the numpy chaos oracle — pipelining may not change
    what executes, only when the host looks at it."""
    from akka_tpu.testkit import chaos

    seed, rate, n = 11, 0.08, 48
    windows = (7, 5, 9)
    rows1, s1, c1, n1 = _chaos_parity_run(1, backend, seed, rate, n, windows)
    rows4, s4, c4, n4 = _chaos_parity_run(4, backend, seed, rate, n, windows)

    assert n1 == n4 == sum(windows)
    np.testing.assert_array_equal(rows1, rows4)
    assert s1.keys() == s4.keys()
    for col in s1:
        np.testing.assert_array_equal(s1[col], s4[col], err_msg=col)
    assert c1 == c4
    # oracle: always-on lanes receive every step; RESUME handles each hit
    lanes = rows1
    expect_failed = int(sum(
        chaos.chaos_hit_np(seed, s, lanes, rate, chaos.CRASH_SALT).sum()
        for s in range(sum(windows))))
    assert c1["failed"] == expect_failed > 0
    assert c1["resumed"] == expect_failed
