"""Schema-evolution serialization (VERDICT r3 #7) — modeled on the
reference's JacksonMigration docs/specs (akka-serialization-jackson
JacksonMigration.scala:22): versioned manifests, payload transforms,
class renames, and a persistence recovery that replays v1 events into a
v2 behavior after a 'rolling upgrade'."""

from dataclasses import dataclass

import pytest

from akka_tpu import ActorSystem
from akka_tpu.persistence import FileJournal
from akka_tpu.serialization import (SchemaMigration, Serialization,
                                    SerializationError,
                                    VersionedJsonSerializer)


# -- v1 application: flat event ----------------------------------------------

@dataclass(frozen=True)
class ItemAddedV1:
    product_id: str
    qty: int


# -- v2 application: nested item + renamed class ------------------------------

@dataclass(frozen=True)
class ItemAppended:  # renamed from ItemAdded in "v2 of the app"
    item: dict  # {"id": ..., "quantity": ...}


class ItemAddedMigration(SchemaMigration):
    current_version = 2

    def transform_class_name(self, from_version, name):
        return "ItemAppended" if from_version < 2 else name

    def transform(self, from_version, payload):
        if from_version < 2:
            payload = {"item": {"id": payload["product_id"],
                                "quantity": payload["qty"]}}
        return payload


def v1_serialization():
    ser = VersionedJsonSerializer()
    ser.register_type(ItemAddedV1, name="ItemAdded")
    s = Serialization(allow_pickle=False)
    s.add_binding(ItemAddedV1, ser)
    return s


def v2_serialization():
    ser = VersionedJsonSerializer()
    ser.register_type(ItemAppended)
    ser.register_migration("ItemAdded", ItemAddedMigration())
    ser.register_migration("ItemAppended", ItemAddedMigration())
    s = Serialization(allow_pickle=False)
    s.add_binding(ItemAppended, ser)
    return s


# -- serializer unit behavior -------------------------------------------------

def test_roundtrip_same_version():
    s = v1_serialization()
    sid, manifest, data = s.serialize(ItemAddedV1("apple", 3))
    assert manifest == "ItemAdded#1"
    back = s.deserialize(sid, manifest, data)
    assert back == ItemAddedV1("apple", 3)


def test_v1_payload_migrates_into_v2_shape():
    sid, manifest, data = v1_serialization().serialize(ItemAddedV1("pear", 2))
    out = v2_serialization().deserialize(sid, manifest, data)
    assert out == ItemAppended(item={"id": "pear", "quantity": 2})


def test_newer_version_is_refused():
    s1 = v1_serialization()
    # a known type stamped with a FUTURE version: refuse (no downgrades)
    with pytest.raises(SerializationError, match="NEWER"):
        s1.deserialize(7, "ItemAdded#2", b'{"product_id":"x","qty":1}')
    # a type this (old) node has never heard of: also a clean failure
    s2 = v2_serialization()
    sid, manifest, data = s2.serialize(ItemAppended({"id": "x",
                                                     "quantity": 1}))
    assert manifest == "ItemAppended#2"
    with pytest.raises(SerializationError, match="unregistered"):
        s1.deserialize(sid, manifest, data)


def test_unregistered_type_fails_fast():
    ser = VersionedJsonSerializer()
    with pytest.raises(SerializationError, match="not registered"):
        ser.to_binary(ItemAddedV1("x", 1))


# -- the rolling-upgrade recovery ---------------------------------------------

def test_v1_journal_replays_into_v2_behavior(tmp_path):
    """Events written by the v1 app (flat ItemAdded) recover correctly in
    the v2 app (nested ItemAppended) through the migration — the
    JacksonMigration journal-upgrade story end to end."""
    d = str(tmp_path / "jv")

    # --- the v1 app writes its journal ---
    from akka_tpu.persistence.journal import AtomicWrite
    from akka_tpu.persistence.messages import PersistentRepr
    j1 = FileJournal(d, serialization=v1_serialization())
    err = j1.write_atomic(AtomicWrite([
        PersistentRepr(ItemAddedV1("apple", 3), 1, "cart-1"),
        PersistentRepr(ItemAddedV1("pear", 2), 2, "cart-1")]))
    assert err is None

    # --- the v2 app (fresh process) replays the same files ---
    j2 = FileJournal(d, serialization=v2_serialization())
    replayed = []
    j2.replay("cart-1", 1, 10, 100, lambda r: replayed.append(r.payload))
    assert replayed == [
        ItemAppended(item={"id": "apple", "quantity": 3}),
        ItemAppended(item={"id": "pear", "quantity": 2})]


def test_v1_journal_recovers_typed_behavior_in_v2_system(tmp_path):
    """Full stack: an EventSourcedBehavior in a v2 system recovers state
    from a journal the v1 system wrote (EventSourcedBehaviorSpec-style)."""
    from akka_tpu.persistence import (EventSourcedBehavior, PersistenceId,
                                      Effect)
    from akka_tpu.persistence.persistence import Persistence
    from akka_tpu.persistence.journal import AtomicWrite
    from akka_tpu.persistence.messages import PersistentRepr
    from akka_tpu.testkit import TestProbe
    from akka_tpu.typed.adapter import props_from_behavior

    d = str(tmp_path / "jfull")
    j1 = FileJournal(d, serialization=v1_serialization())
    assert j1.write_atomic(AtomicWrite([
        PersistentRepr(ItemAddedV1("apple", 3), 1, "Cart|c9"),
        PersistentRepr(ItemAddedV1("pear", 2), 2, "Cart|c9")])) is None

    plugin_id = "test.versioned-journal"
    Persistence.register_journal_plugin(
        plugin_id, lambda _system, _cfg: FileJournal(
            d, serialization=v2_serialization()))

    cfg = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0,
                    "persistence": {
                        "journal": {"plugin": plugin_id},
                        "snapshot-store": {
                            "plugin":
                                "akka.persistence.snapshot-store.inmem"}}}}
    system = ActorSystem.create("versioned-upgrade", cfg)
    try:
        probe = TestProbe(system)

        def command_handler(state, cmd):
            return Effect.reply(cmd, ("cart", state))

        def event_handler(state, event):
            # the v2 handler understands ONLY the v2 event shape
            assert isinstance(event, ItemAppended), event
            return state + [(event.item["id"], event.item["quantity"])]

        beh = EventSourcedBehavior(PersistenceId.of("Cart", "c9"), [],
                                   command_handler, event_handler)
        ref = system.actor_of(props_from_behavior(beh), "cart")
        ref.tell(probe.ref)
        assert probe.receive_one(10.0) == \
            ("cart", [("apple", 3), ("pear", 2)])
    finally:
        system.terminate()
        system.await_termination(10.0)
