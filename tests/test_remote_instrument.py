"""RemoteInstrument wire SPI (VERDICT r3 #8) — modeled on the reference's
artery RemoteInstrument contract (RemoteInstrument.scala:32): reserved
per-message header space, serialize-time write + deliver-time read, and a
trace-context propagation across two REAL processes."""

import pytest

from akka_tpu import Actor, ActorSystem, Props, ask_sync
from akka_tpu.remote.instrument import RemoteInstrument, RemoteInstruments
from akka_tpu.remote.transport import InProcTransport, WireEnvelope
from akka_tpu.testkit.multi_process import spawn_nodes


# -- wire format --------------------------------------------------------------

def test_envelope_metadata_roundtrip():
    env = WireEnvelope(recipient="akka://sys@h:1/user/a", sender=None,
                       serializer_id=4, manifest="m", payload=b"xyz",
                       metadata={1: b"trace-123", 7: b"\x00\x01"})
    back = WireEnvelope.from_bytes(env.to_bytes())
    assert back.metadata == {1: b"trace-123", 7: b"\x00\x01"}
    assert back.payload == b"xyz"
    assert back.recipient == env.recipient


def test_envelope_without_metadata_unchanged():
    env = WireEnvelope(recipient="r", sender="s", serializer_id=2,
                       manifest="", payload=b"p")
    back = WireEnvelope.from_bytes(env.to_bytes())
    assert back.metadata is None
    assert back.sender == "s"


def test_identifier_range_enforced():
    class Bad(RemoteInstrument):
        identifier = 32

    with pytest.raises(ValueError, match="1..31"):
        RemoteInstruments([Bad()])

    class A(RemoteInstrument):
        identifier = 3

    with pytest.raises(ValueError, match="duplicate"):
        RemoteInstruments([A(), A()])


# -- in-process two-system propagation ---------------------------------------

class TraceInstrument(RemoteInstrument):
    identifier = 9

    def __init__(self):
        self.current = None      # what this side stamps on sends
        self.seen = []           # (metadata, message) read on receives
        self.sent = []
        self.received = []

    def remote_write_metadata(self, recipient, message, sender):
        return self.current.encode() if self.current else None

    def remote_read_metadata(self, recipient, message, sender, metadata):
        self.seen.append((metadata.decode(), message))

    def remote_message_sent(self, recipient, message, sender, size):
        self.sent.append(size)

    def remote_message_received(self, recipient, message, sender, size):
        self.received.append(size)


class Echo(Actor):
    def receive(self, message):
        self.sender.tell(("echo", message), self.self_ref)


def remote_system(name):
    return ActorSystem.create(name, {
        "akka": {"actor": {"provider": "remote"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}}}})


def test_trace_id_propagates_between_systems():
    InProcTransport.fault_injector.reset()
    a = remote_system("insA")
    b = remote_system("insB")
    try:
        ia, ib = TraceInstrument(), TraceInstrument()
        a.provider.remote_instruments.add(ia)
        b.provider.remote_instruments.add(ib)
        b.actor_of(Props.create(Echo), "echo")
        baddr = b.provider.local_address
        ref = a.provider.resolve_actor_ref(
            f"akka://insB@{baddr.host}:{baddr.port}/user/echo")

        ia.current = "trace-42"
        assert ask_sync(ref, "ping", timeout=10.0, system=a) \
            == ("echo", "ping")
        # the receiving side's same-identifier instrument read the stamp
        assert ("trace-42", "ping") in ib.seen
        assert ia.sent and ib.received  # timing hooks fired
    finally:
        for s in (a, b):
            s.terminate()
        for s in (a, b):
            assert s.await_termination(10.0)
        InProcTransport.fault_injector.reset()


# -- real two-process propagation ---------------------------------------------

@pytest.mark.slow
def test_trace_id_propagates_across_real_processes():
    worker = r"""
import json, os, sys, time
from akka_tpu import Actor, ActorSystem, Props, ask_sync
from akka_tpu.remote.instrument import RemoteInstrument
from akka_tpu.testkit.multi_process import (node_barrier, node_index,
                                            node_result)

IDX = node_index()
BASE_PORT = int(os.environ["AKKA_TPU_TEST_BASE_PORT"])

class TraceInstrument(RemoteInstrument):
    identifier = 9
    def __init__(self):
        self.current = None
        self.seen = []
    def remote_write_metadata(self, recipient, message, sender):
        return self.current.encode() if self.current else None
    def remote_read_metadata(self, recipient, message, sender, metadata):
        self.seen.append(metadata.decode())

system = ActorSystem.create(f"ri{IDX}", {
    "akka": {"actor": {"provider": "remote"},
             "stdout-loglevel": "OFF", "log-dead-letters": 0,
             "remote": {"transport": "tcp",
                        "canonical": {"hostname": "127.0.0.1",
                                      "port": BASE_PORT + IDX}}}})
ins = TraceInstrument()
system.provider.remote_instruments.add(ins)

class Echo(Actor):
    def receive(self, message):
        self.sender.tell(("echo", message), self.self_ref)

if IDX == 0:
    system.actor_of(Props.create(Echo), "echo")
    node_barrier("ready")
    node_barrier("asked")
    node_result({"seen": ins.seen})
else:
    node_barrier("ready")
    ref = system.provider.resolve_actor_ref(
        f"akka://ri0@127.0.0.1:{BASE_PORT}/user/echo")
    ins.current = "xproc-trace-7"
    reply = ask_sync(ref, "hello", timeout=20.0, system=system)
    assert reply == ("echo", "hello"), reply
    node_barrier("asked")
    node_result({"sent": ins.current})
node_barrier("done")
system.terminate(); system.await_termination(10)
"""
    results, _ = spawn_nodes(worker, 2, timeout=120.0,
                             extra_env={"AKKA_TPU_TEST_BASE_PORT": "23710"})
    # node 0 (the echo host) read the trace id node 1 stamped on the wire
    assert "xproc-trace-7" in results[0]["seen"]
    assert results[1]["sent"] == "xproc-trace-7"
