"""ddata tests — modeled on the reference's unit specs
(akka-distributed-data/src/test/scala: GCounterSpec, PNCounterSpec, ORSetSpec,
ORMapSpec, LWWRegisterSpec, VersionVectorSpec) and multi-jvm ReplicatorSpec,
run over the in-proc transport; tensor-bank kernels on the virtual 8-dev mesh."""

import time

import jax
import jax.numpy as jnp
import pytest

from akka_tpu import ActorSystem
from akka_tpu.cluster import Cluster
from akka_tpu.ddata import (Changed, Delete, DeleteSuccess, DataDeleted, Deleted,
                            DistributedData, Flag, GCounter, Get, GetDataDeleted,
                            GetSuccess, GSet, Key, LWWMap, LWWRegister, NotFound,
                            ORMap, ORMultiMap, ORSet, Ordering, PNCounter,
                            PNCounterMap, ReadAll, ReadLocal, ReadMajority,
                            Subscribe, Update, UpdateSuccess, VersionVector,
                            WriteAll, WriteLocal, WriteMajority, tensor)
from akka_tpu.ddata.durable import DurableStore
from akka_tpu.remote.transport import InProcTransport
from akka_tpu.testkit import TestProbe, await_condition

N1, N2, N3 = "n1", "n2", "n3"

FAST = {"akka": {"actor": {"provider": "cluster"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}},
                 "cluster": {"gossip-interval": "0.05s",
                             "leader-actions-interval": "0.05s",
                             "unreachable-nodes-reaper-interval": "0.1s",
                             "failure-detector": {
                                 "heartbeat-interval": "0.1s",
                                 "acceptable-heartbeat-pause": "2s"},
                             "distributed-data": {
                                 "gossip-interval": "0.1s",
                                 "notify-subscribers-interval": "0.05s",
                                 "pruning-interval": "0.3s",
                                 "delta-crdt": {
                                     "delta-propagation-interval": "0.05s"}}}}}


# -- version vector ----------------------------------------------------------

def test_version_vector_ordering():
    v1 = VersionVector.empty().increment(N1)
    v2 = v1.increment(N2)
    assert v1.compare_to(v2) == Ordering.BEFORE
    assert v2.compare_to(v1) == Ordering.AFTER
    assert v1.compare_to(v1) == Ordering.SAME
    a = VersionVector.empty().increment(N1)
    b = VersionVector.empty().increment(N2)
    assert a.compare_to(b) == Ordering.CONCURRENT
    m = a.merge(b)
    assert m.is_after(a) and m.is_after(b)


# -- counters ----------------------------------------------------------------

def test_gcounter_merge_idempotent_commutative():
    a = GCounter.empty().increment(N1, 3)
    b = GCounter.empty().increment(N2, 5)
    assert a.merge(b).value == 8
    assert b.merge(a).value == 8
    assert a.merge(b).merge(b).value == 8  # idempotent
    # concurrent increments on the same node: max wins (state-based)
    a2 = a.increment(N1, 2)
    assert a2.merge(a).value == 5

    with pytest.raises(ValueError):
        a.increment(N1, -1)


def test_gcounter_delta():
    a = GCounter.empty().increment(N1, 1).increment(N1, 2)
    d = a.delta
    assert d is not None and d.value == 3
    other = GCounter.empty().increment(N2, 7)
    assert other.merge_delta(d).value == 10
    assert a.reset_delta().delta is None


def test_pncounter():
    c = PNCounter.empty().increment(N1, 10).decrement(N1, 3).decrement(N2, 2)
    assert c.value == 5
    other = PNCounter.empty().increment(N2, 1)
    assert c.merge(other).value == 6
    # prune collapses removed node's contributions
    pruned = c.prune(N2, N1)
    assert pruned.value == c.value
    assert N2 not in pruned.modified_by_nodes()


# -- sets --------------------------------------------------------------------

def test_gset():
    a = GSet.empty().add("x").add("y")
    b = GSet.empty().add("z")
    m = a.merge(b)
    assert m.elements == {"x", "y", "z"}
    assert "x" in m


def test_orset_add_remove():
    s = ORSet.empty().add(N1, "a").add(N1, "b").remove(N1, "a")
    assert s.elements == {"b"}
    assert s.merge(s).elements == {"b"}


def test_orset_add_wins_over_concurrent_remove():
    base = ORSet.empty().add(N1, "x")
    # replica 1 removes x; replica 2 concurrently re-adds x
    r1 = base.remove(N1, "x")
    r2 = base.add(N2, "x")
    assert r1.merge(r2).elements == {"x"}
    assert r2.merge(r1).elements == {"x"}


def test_orset_remove_propagates():
    base = ORSet.empty().add(N1, "x").add(N1, "y")
    removed = base.remove(N1, "x")
    # replica that only saw the adds converges to the remove
    assert base.merge(removed).elements == {"y"}
    assert removed.merge(base).elements == {"y"}


def test_orset_prune():
    s = ORSet.empty().add(N1, "a").add(N2, "b")
    p = s.prune(N2, N1)
    assert p.elements == {"a", "b"}
    assert N2 not in p.modified_by_nodes()


# -- registers, flag, maps ---------------------------------------------------

def test_flag_and_lww():
    assert Flag.empty().merge(Flag.empty().switch_on()).enabled
    r1 = LWWRegister.create(N1, "v1", clock=lambda c, v: 1)
    r2 = r1.with_value(N2, "v2", clock=lambda c, v: 2)
    assert r1.merge(r2).value == "v2"
    assert r2.merge(r1).value == "v2"
    # same timestamp: lowest node id wins (deterministic tiebreak)
    ra = LWWRegister(N1, "a", 5)
    rb = LWWRegister(N2, "b", 5)
    assert ra.merge(rb).value == "a"
    assert rb.merge(ra).value == "a"


def test_ormap_and_friends():
    m = ORMap.empty().put(N1, "k1", GCounter.empty().increment(N1, 2))
    m2 = ORMap.empty().put(N2, "k1", GCounter.empty().increment(N2, 3))
    merged = m.merge(m2)
    assert merged.get("k1").value == 5
    removed = merged.remove(N1, "k1")
    assert "k1" not in removed

    mm = (ORMultiMap.empty().add_binding(N1, "k", 1).add_binding(N1, "k", 2)
          .remove_binding(N1, "k", 1))
    assert mm.get("k") == {2}

    pm = PNCounterMap.empty().increment(N1, "a", 3).decrement(N1, "a", 1)
    assert pm.get("a") == 2
    assert pm.merge(PNCounterMap.empty().increment(N2, "a", 1)).get("a") == 3

    lm = LWWMap.empty().put(N1, "k", "v", clock=lambda c, v: 1)
    lm2 = lm.put(N2, "k", "w", clock=lambda c, v: 2)
    assert lm.merge(lm2).get("k") == "w"


# -- tensor banks ------------------------------------------------------------

def test_tensor_gcounter_bank():
    n_keys, n_nodes = 16, 4
    a = jnp.zeros((n_keys, n_nodes), jnp.uint32)
    a = tensor.gcounter_increment(a, 0, jnp.array([1, 1, 5]), jnp.array([2, 3, 7]))
    b = jnp.zeros((n_keys, n_nodes), jnp.uint32)
    b = tensor.gcounter_increment(b, 2, jnp.array([1]), jnp.array([10]))
    m = tensor.gcounter_merge(a, b)
    vals = tensor.gcounter_value(m)
    assert int(vals[1]) == 15 and int(vals[5]) == 7
    # idempotent + commutative
    assert (tensor.gcounter_merge(m, a) == m).all()
    assert (tensor.gcounter_merge(b, a) == m).all()


def test_tensor_converge_over_mesh():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs virtual multi-device mesh")
    from jax.sharding import Mesh
    n = 4
    mesh = Mesh(devs[:n], ("replica",))
    n_keys, n_nodes = 8, n
    # each replica has incremented its own node column locally
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    host = np.zeros((n, n_keys, n_nodes), np.uint32)
    for r in range(n):
        host[r, :, r] = r + 1
    stacked = jax.device_put(jnp.asarray(host),
                             NamedSharding(mesh, P("replica")))
    converged = tensor.converge_over_mesh(stacked, mesh)
    out = np.asarray(converged)
    # every replica sees the join: column r == r+1 for all keys
    for r in range(n):
        assert (out[r] == out[0]).all()
        assert (out[0][:, r] == r + 1).all()
    # value = sum over node columns
    assert (np.asarray(tensor.gcounter_value(converged[0])) ==
            sum(range(1, n + 1))).all()


# -- durable store -----------------------------------------------------------

def test_durable_store_roundtrip(tmp_path):
    store = DurableStore(str(tmp_path))
    g = GCounter.empty().increment(N1, 42)
    store.store("counter", g)
    store.store("set", GSet.empty().add("x"))
    loaded = DurableStore(str(tmp_path)).load_all()
    assert loaded["counter"].value == 42
    assert loaded["set"].elements == {"x"}
    store.delete("counter")
    assert "counter" not in DurableStore(str(tmp_path)).load_all()


# -- replicator (multi-node over in-proc transport) --------------------------

@pytest.fixture()
def ddata_nodes():
    InProcTransport.fault_injector.reset()
    systems = [ActorSystem.create(f"dd{i}", FAST) for i in range(3)]
    clusters = [Cluster.get(s) for s in systems]
    first = str(systems[0].provider.local_address)
    for c in clusters:
        c.join(first)
    await_condition(
        lambda: all(len([m for m in c.state.members
                         if m.status.value == "Up"]) == 3 for c in clusters),
        max_time=10.0)
    dd = [DistributedData.get(s) for s in systems]
    yield systems, dd
    for s in systems:
        s.terminate()
    for s in systems:
        s.await_termination(10.0)
    InProcTransport.fault_injector.reset()


def _node_id(system):
    """uid-qualified CRDT node id (what DistributedData.self_unique_address
    exposes) — the id pruning recognises after the member is removed."""
    from akka_tpu.ddata.replicator import unique_node_id
    return unique_node_id(Cluster.get(system).self_unique_address)


def test_replicator_update_and_gossip_convergence(ddata_nodes):
    systems, dd = ddata_nodes
    key = Key("counter")
    probe = TestProbe(systems[0])
    nid = _node_id(systems[0])
    dd[0].replicator.tell(
        Update(key, GCounter.empty(), WriteLocal(),
               lambda c: c.increment(nid, 5)), probe.ref)
    assert isinstance(probe.expect_msg_class(UpdateSuccess, 3.0), UpdateSuccess)

    # gossip/delta propagates to the other nodes
    def replicated_everywhere():
        oks = []
        for i in (1, 2):
            p = TestProbe(systems[i])
            dd[i].replicator.tell(Get(key, ReadLocal()), p.ref)
            m = p.receive_one(2.0)
            oks.append(isinstance(m, GetSuccess) and m.data.value == 5)
        return all(oks)
    await_condition(replicated_everywhere, max_time=10.0)


def test_replicator_write_majority_read_majority(ddata_nodes):
    systems, dd = ddata_nodes
    key = Key("orset")
    p0 = TestProbe(systems[0])
    nid0 = _node_id(systems[0])
    dd[0].replicator.tell(
        Update(key, ORSet.empty(), WriteMajority(3.0),
               lambda s: s.add(nid0, "alpha")), p0.ref)
    p0.expect_msg_class(UpdateSuccess, 5.0)

    # WriteMajority(3 nodes) = self + 1 remote, so a majority read from any
    # node must observe the element
    p1 = TestProbe(systems[1])
    dd[1].replicator.tell(Get(key, ReadMajority(3.0)), p1.ref)
    got = p1.expect_msg_class(GetSuccess, 5.0)
    assert "alpha" in got.data.elements


def test_replicator_write_all_read_local(ddata_nodes):
    systems, dd = ddata_nodes
    key = Key("flag")
    p = TestProbe(systems[2])
    dd[2].replicator.tell(
        Update(key, Flag.empty(), WriteAll(5.0), lambda f: f.switch_on()), p.ref)
    p.expect_msg_class(UpdateSuccess, 6.0)
    for i in range(3):
        pi = TestProbe(systems[i])
        dd[i].replicator.tell(Get(key, ReadLocal()), pi.ref)
        assert pi.expect_msg_class(GetSuccess, 2.0).data.enabled


def test_replicator_subscribe_changed(ddata_nodes):
    systems, dd = ddata_nodes
    key = Key("subbed")
    sub = TestProbe(systems[1])
    dd[1].replicator.tell(Subscribe(key, sub.ref), None)
    nid0 = _node_id(systems[0])
    p = TestProbe(systems[0])
    dd[0].replicator.tell(
        Update(key, PNCounter.empty(), WriteLocal(),
               lambda c: c.increment(nid0, 9)), p.ref)
    p.expect_msg_class(UpdateSuccess, 3.0)
    changed = sub.expect_msg_class(Changed, 10.0)
    assert changed.key == key and changed.data.value == 9


def test_replicator_get_notfound_and_delete(ddata_nodes):
    systems, dd = ddata_nodes
    p = TestProbe(systems[0])
    dd[0].replicator.tell(Get(Key("missing"), ReadLocal()), p.ref)
    assert isinstance(p.receive_one(2.0), NotFound)

    key = Key("doomed")
    nid = _node_id(systems[0])
    dd[0].replicator.tell(
        Update(key, GCounter.empty(), WriteAll(5.0),
               lambda c: c.increment(nid, 1)), p.ref)
    p.expect_msg_class(UpdateSuccess, 6.0)
    dd[0].replicator.tell(Delete(key, WriteAll(5.0)), p.ref)
    p.expect_msg_class(DeleteSuccess, 6.0)
    # all nodes see the tombstone; further updates rejected
    for i in range(3):
        pi = TestProbe(systems[i])
        dd[i].replicator.tell(Get(key, ReadLocal()), pi.ref)
        assert isinstance(pi.receive_one(2.0), GetDataDeleted)
    dd[0].replicator.tell(Delete(key, WriteLocal()), p.ref)
    assert isinstance(p.receive_one(2.0), DataDeleted)


def test_replicator_prunes_removed_node_without_double_count(ddata_nodes):
    """Reference semantics (PruningState): after a member is removed, the
    leader collapses its CRDT contributions into itself; stale copies must
    not resurrect the removed node's entries (no double count)."""
    systems, dd = ddata_nodes
    key = Key("pruned-counter")
    # every node contributes 1 -> value 3, replicated everywhere
    for i in range(3):
        p = TestProbe(systems[i])
        nid = _node_id(systems[i])
        dd[i].replicator.tell(
            Update(key, GCounter.empty(), WriteAll(5.0),
                   lambda c, nid=nid: c.increment(nid, 1)), p.ref)
        p.expect_msg_class(UpdateSuccess, 6.0)

    # node 2 leaves the cluster for good
    gone = _node_id(systems[2])
    systems[2].terminate()
    systems[2].await_termination(10.0)
    Cluster.get(systems[0]).down(gone)
    await_condition(
        lambda: all(gone not in [str(m.address) for m in
                                 Cluster.get(s).state.members]
                    for s in systems[:2]), max_time=10.0)

    def pruned_everywhere():
        ok = []
        for i in (0, 1):
            p = TestProbe(systems[i])
            dd[i].replicator.tell(Get(key, ReadLocal()), p.ref)
            m = p.receive_one(2.0)
            ok.append(isinstance(m, GetSuccess) and m.data.value == 3
                      and gone not in m.data.modified_by_nodes())
        return all(ok)
    await_condition(pruned_everywhere, max_time=15.0)


# -- op-based ORSet deltas (r5; reference: ORSet.scala:55-110,334-501) --------

def test_orset_add_delta_ships_only_the_touched_element():
    from akka_tpu.ddata.crdt import ORSet, ORSetAddDeltaOp
    s = ORSet.empty()
    for e in ("a", "b", "c", "d", "e"):
        s = s.add("n1", e).reset_delta()
    s2 = s.add("n1", "f")
    op = s2.delta
    assert isinstance(op, ORSetAddDeltaOp)
    # the op carries ONE element + its dot, not the 6-element set
    assert set(op.underlying.element_map) == {"f"}
    assert list(op.underlying.vvector.nodes()) == ["n1"]
    # a replica applies it and converges with the full state
    replica = s.reset_delta()
    assert replica.merge_delta(op).elements == s2.elements


def test_orset_consecutive_adds_coalesce_into_one_op():
    from akka_tpu.ddata.crdt import ORSet, ORSetAddDeltaOp
    s = ORSet.empty().add("n1", "x").add("n1", "y").add("n1", "z")
    op = s.delta
    assert isinstance(op, ORSetAddDeltaOp)  # one op, not a group of three
    assert set(op.underlying.element_map) == {"x", "y", "z"}
    assert ORSet.empty().merge_delta(op).elements == {"x", "y", "z"}


def test_orset_remove_delta_wins_only_over_observed_adds():
    from akka_tpu.ddata.crdt import ORSet
    a = ORSet.empty().add("n1", "e").reset_delta()
    b = a  # replica
    # n1 removes e; CONCURRENTLY n2 re-adds e on its replica
    removed = a.remove("n1", "e")
    rm_op = removed.delta
    readded = b.add("n2", "e").reset_delta()
    # the remove only observed n1's add: applying it to the replica that
    # saw a CONCURRENT re-add keeps the element (add-wins)
    after = readded.merge_delta(rm_op)
    assert "e" in after.elements
    # but a replica with no concurrent add drops it
    assert "e" not in b.merge_delta(rm_op).elements


def test_orset_mixed_ops_group_in_order():
    from akka_tpu.ddata.crdt import ORSet, ORSetDeltaGroup
    s = ORSet.empty().add("n1", "x").remove("n1", "x").add("n1", "y")
    group = s.delta
    assert isinstance(group, ORSetDeltaGroup)
    applied = ORSet.empty().merge_delta(group)
    assert applied.elements == {"y"}  # x added then removed, y stays


def test_orset_delta_first_sight_applies_against_zero():
    """A replica that has never seen the key gets the op-based delta and
    applies it against ReplicatedDelta.zero semantics."""
    from akka_tpu.ddata.crdt import ORSet
    s = ORSet.empty().add("n1", "only")
    op = s.delta
    fresh = op.zero().merge_delta(op)
    assert fresh.elements == {"only"}


def test_orset_clear_ships_full_state_op():
    from akka_tpu.ddata.crdt import ORSet, ORSetFullStateDeltaOp
    base = ORSet.empty().add("n1", "a").add("n1", "b").reset_delta()
    stale = base  # a true replica shares the causal history
    cleared = base.clear()
    op = cleared.delta
    assert isinstance(op, ORSetFullStateDeltaOp)
    assert stale.merge_delta(op).elements == frozenset()


def test_delta_gap_falls_back_to_gossip_without_data_loss(ddata_nodes):
    """The op-delta causal guard (code-review r5 finding): a replica that
    MISSES a delta tick must not apply the next op (the op's vvector would
    claim the missed events and delete their elements cluster-wide);
    instead it drops gapped ops and converges via full-state gossip —
    every element survives on every node."""
    from akka_tpu.ddata.replicator import _DeltaPropagation
    systems, dd = ddata_nodes
    key = Key("gapset")
    probe = TestProbe(systems[0])
    me = _node_id(systems[0])
    dd[0].replicator.tell(
        Update(key, ORSet.empty(), WriteLocal(),
               modify=lambda s: s.add(me, "a")), probe.ref)
    probe.fish_for_message(lambda m: isinstance(m, UpdateSuccess), 5.0)
    # forge the gap on node 1: inject a delta claiming seq 2 from node 0
    # BEFORE node 1 ever saw seq 1 (as if the first tick was dropped)
    s_b = ORSet.empty().add(me, "x").reset_delta().add(me, "b")
    dd[1].replicator.tell(
        _DeltaPropagation({key.id: (99, s_b.delta)},  # seq 99: a huge gap
                          from_addr=str(systems[0].provider.local_address),
                          origin_uid="forged-origin"),
        dd[0].replicator)

    # node 1 must NEVER apply the gapped op (no b, no x), and gossip must
    # still converge the real element 'a' — nothing lost, nothing forged
    def state_on_1():
        p = TestProbe(systems[1])
        dd[1].replicator.tell(Get(key, ReadLocal()), p.ref)
        try:
            got = p.receive_one(1.0)
        except AssertionError:
            return None
        return got.data.elements if isinstance(got, GetSuccess) else None

    await_condition(lambda: state_on_1() == frozenset({"a"}), max_time=10.0,
                    message=f"expected exactly {{'a'}}: {state_on_1()}")
    assert state_on_1() == frozenset({"a"})  # gapped op never applied


def test_remote_delete_prunes_delta_cursors(ddata_nodes):
    """A key deleted REMOTELY (the tombstone arrives via replicated _Write /
    gossip, not a local Delete call) must drop the key's delta bookkeeping
    on the receiving replica — `_delta_seen`/`_delta_gapped` cursors and the
    pending-delta buffers would otherwise grow with key churn, and stale
    gossip must not re-add a cursor for a dead key."""
    from akka_tpu.ddata.replicator import DELETED, _Gossip
    systems, dd = ddata_nodes
    key = Key("churned")
    me = _node_id(systems[0])
    p = TestProbe(systems[0])
    dd[0].replicator.tell(
        Update(key, ORSet.empty(), WriteAll(5.0),
               modify=lambda s: s.add(me, "a")), p.ref)
    p.expect_msg_class(UpdateSuccess, 6.0)

    # delete on node 0: nodes 1/2 only ever see the tombstone remotely
    dd[0].replicator.tell(Delete(key, WriteAll(5.0)), p.ref)
    p.expect_msg_class(DeleteSuccess, 6.0)

    def pruned_everywhere():
        for i in (1, 2):
            rep = dd[i].replicator.cell.actor
            if rep.data.get(key.id) != DELETED:
                return False
            if any(pr[2] == key.id for pr in rep._delta_seen):
                return False
            if any(pr[2] == key.id for pr in rep._delta_gapped):
                return False
            if key.id in rep.deltas or key.id in rep.delta_seq:
                return False
        return True
    await_condition(pruned_everywhere, max_time=10.0)

    # forged stale gossip: the dead key rides in WITH a delta cursor. The
    # tombstone must win and no cursor may be re-created for it.
    stale = ORSet.empty().add(me, "zombie")
    dd[1].replicator.tell(
        _Gossip({key.id: stale}, want_keys=(),
                from_addr=str(systems[0].provider.local_address),
                tombstones={}, delta_seq={key.id: 7},
                origin_uid="stale-uid"),
        dd[0].replicator)
    # a Get round-trip on the same mailbox orders after the gossip
    p1 = TestProbe(systems[1])
    dd[1].replicator.tell(Get(key, ReadLocal()), p1.ref)
    assert isinstance(p1.receive_one(3.0), GetDataDeleted)
    rep1 = dd[1].replicator.cell.actor
    assert not any(pr[2] == key.id for pr in rep1._delta_seen)
    assert not any(pr[2] == key.id for pr in rep1._delta_gapped)


# -- op-based ORMap-family deltas (r14; reference: ORMap.scala:30-110) --------

def test_ormap_update_delta_ships_only_the_touched_entry():
    from akka_tpu.ddata.crdt import ORMapUpdateDeltaOp
    m = PNCounterMap.empty()
    for i in range(50):
        m = m.increment("n1", f"k{i}", i + 1).reset_delta()
    m2 = m.increment("n1", "k3", 5)
    op = m2.delta
    assert isinstance(op, ORMapUpdateDeltaOp)
    # the op carries ONE key's value delta, not the 50-entry map
    assert set(op.values) == {"k3"}
    replica = m.merge_delta(op)
    assert replica.get("k3") == m2.get("k3")
    assert replica.entries == m2.entries


def test_ormap_consecutive_updates_coalesce_into_one_op():
    from akka_tpu.ddata.crdt import ORMapUpdateDeltaOp
    m = (PNCounterMap.empty()
         .increment("n1", "a", 1).increment("n1", "a", 2)
         .increment("n1", "b", 3))
    op = m.delta
    assert isinstance(op, ORMapUpdateDeltaOp)  # one op, not a group of three
    assert set(op.values) == {"a", "b"}
    fresh = op.zero().merge_delta(op)
    assert fresh.get("a") == 3 and fresh.get("b") == 3


def test_ormap_delta_first_sight_reconstructs_wrapper_from_zero_tag():
    """The zero-tag edge: a replica that has never seen the key applies the
    op against `op.zero()` and must get back the proper WRAPPER type (the
    derived map, not a bare ORMap)."""
    pm = PNCounterMap.empty().increment("n1", "k", 7)
    fresh = pm.delta.zero().merge_delta(pm.delta)
    assert isinstance(fresh, PNCounterMap) and fresh.get("k") == 7

    mm = ORMultiMap.empty().add_binding("n1", "k", "v")
    fresh = mm.delta.zero().merge_delta(mm.delta)
    assert isinstance(fresh, ORMultiMap) and fresh.get("k") == frozenset({"v"})

    lm = LWWMap.empty().put("n1", "k", "v", clock=lambda c, v: 1)
    fresh = lm.delta.zero().merge_delta(lm.delta)
    assert isinstance(fresh, LWWMap) and fresh.get("k") == "v"


def test_ormap_mixed_ops_group_in_order():
    from akka_tpu.ddata.crdt import ORMapDeltaGroup
    m = (ORMap.empty()
         .put("n1", "a", GCounter.empty().increment("n1", 1))
         .remove("n1", "a")
         .put("n1", "b", GCounter.empty().increment("n1", 2)))
    group = m.delta
    assert isinstance(group, ORMapDeltaGroup)
    applied = ORMap.empty().merge_delta(group)
    assert set(applied.entries) == {"b"}  # a put then removed, b stays


def test_ormap_concurrent_put_put_same_key_converges():
    """Concurrent puts of the same key on two replicas must converge to the
    same winner on both, op path and full-state path alike."""
    base = LWWMap.empty().put("a", "k", "v0", clock=lambda c, v: 1).reset_delta()
    pa = base.put("a", "k", "va", clock=lambda c, v: 2)
    pb = base.put("b", "k", "vb", clock=lambda c, v: 3)
    via_ops_1 = base.merge_delta(pa.delta).merge_delta(pb.delta)
    via_ops_2 = base.merge_delta(pb.delta).merge_delta(pa.delta)
    via_full = pa.reset_delta().merge(pb.reset_delta())
    assert via_ops_1.get("k") == via_ops_2.get("k") == via_full.get("k") == "vb"


def test_ormultimap_concurrent_remove_vs_rebind_converges():
    """The tombstone edge (withValueDeltas semantics): node a removes the
    key while node b concurrently re-binds a new value — both delivery
    orders and the full-state merge agree on {new value}."""
    base = ORMultiMap.empty().add_binding("a", "k", "x").reset_delta()
    ra = base.remove("a", "k")
    rb = base.add_binding("b", "k", "y")
    c1 = base.merge_delta(ra.delta).merge_delta(rb.delta)
    c2 = base.merge_delta(rb.delta).merge_delta(ra.delta)
    full = ra.reset_delta().merge(rb.reset_delta())
    assert c1.entries == c2.entries == full.entries == {"k": frozenset({"y"})}


def test_ormap_family_op_vs_full_parity_random_interleavings():
    """Property parity: random op interleavings on 3 replicas, synced via
    op deltas, must converge to the same state the full-state merges
    produce. (PNCounterMap avoids concurrent remove+increment of the same
    key — a documented Akka-parity anomaly reconciled only by gossip.)"""
    import random
    rng = random.Random(1405)
    nodes = ["n1", "n2", "n3"]

    def run(make_empty, mutate):
        states = {n: make_empty() for n in nodes}
        pending = {n: [] for n in nodes}
        for _ in range(90):
            n = rng.choice(nodes)
            s = mutate(rng, n, states[n].reset_delta())
            if s.delta is not None:
                pending[n].append(s.delta)
            states[n] = s
            if rng.random() < 0.3:  # deliver one node's ops, in order
                src = rng.choice(nodes)
                for dst in nodes:
                    if dst is src:
                        continue
                    acc = states[dst].reset_delta()
                    for d in pending[src]:
                        acc = acc.merge_delta(d)
                    states[dst] = acc
        # final full-state anti-entropy must be a no-op fixpoint
        conv = states["n1"].reset_delta()
        for n in ("n2", "n3"):
            conv = conv.merge(states[n].reset_delta())
        for n in nodes:
            assert states[n].reset_delta().merge(conv).entries == conv.entries

    def mut_multimap(rng, n, s):
        k = f"k{rng.randrange(5)}"
        r = rng.random()
        if r < 0.5:
            return s.add_binding(n, k, rng.randrange(8))
        if r < 0.7:
            vs = s.get(k)
            return s.remove_binding(n, k, sorted(vs)[0]) if vs else s
        if r < 0.85:
            return s.put(n, k, [rng.randrange(8)])
        return s.remove(n, k)

    def mut_counter(rng, n, s):
        k = f"k{rng.randrange(5)}"
        return (s.increment(n, k, rng.randrange(1, 4)) if rng.random() < 0.7
                else s.decrement(n, k, 1))

    def mut_lww(rng, n, s):
        k = f"k{rng.randrange(5)}"
        t = [0]

        def clock(c, v):
            t[0] = max(c, t[0]) + 1
            return t[0]
        if rng.random() < 0.8:
            return s.put(n, k, rng.randrange(100), clock=clock)
        return s.remove(n, k) if s.get(k) is not None else s

    def mut_ormap(rng, n, s):
        k = f"k{rng.randrange(5)}"
        if rng.random() < 0.8:
            return s.updated(n, k, ORSet.empty(),
                             lambda o: o.add(n, rng.randrange(8)))
        return s.remove(n, k)

    run(ORMultiMap.empty, mut_multimap)
    run(PNCounterMap.empty, mut_counter)
    run(LWWMap.empty, mut_lww)
    run(ORMap.empty, mut_ormap)


def test_ormultimap_one_entry_delta_budget_on_10k_map():
    """The O(entry)-not-O(map) claim, measured: a 1-entry update to a
    10k-entry ORMultiMap must serialize to <= 2% of the full map."""
    import pickle
    m = ORMultiMap.empty()
    for i in range(10000):
        m = m.add_binding("n1", f"key-{i}", i).reset_delta()
    m2 = m.add_binding("n1", "key-7", 10**6)
    delta_bytes = len(pickle.dumps(m2.delta))
    full_bytes = len(pickle.dumps(m2.reset_delta()))
    assert delta_bytes <= 0.02 * full_bytes, (delta_bytes, full_bytes)
    # and the tiny delta is sufficient: a replica converges from it alone
    assert m.merge_delta(m2.delta).get("key-7") == m2.get("key-7")


def test_replicator_ships_ormap_op_deltas(ddata_nodes):
    """End to end through the replicator's delta-propagation cursors: a
    PNCounterMap update on node 0 must arrive at nodes 1/2 as an op delta
    (not full-state gossip) and converge."""
    from akka_tpu.ddata.crdt import ORMapDeltaOp
    systems, dd = ddata_nodes
    key = Key("hotmap")
    me = _node_id(systems[0])
    p = TestProbe(systems[0])
    dd[0].replicator.tell(
        Update(key, PNCounterMap.empty(), WriteLocal(),
               modify=lambda m: m.increment(me, "ent-1", 5)), p.ref)
    p.expect_msg_class(UpdateSuccess, 5.0)
    # the pending delta buffered for propagation is an op, not a snapshot
    rep0 = dd[0].replicator.cell.actor
    acc = rep0.deltas.get(key.id)
    assert acc is None or isinstance(acc, ORMapDeltaOp)

    def converged():
        ok = []
        for i in (1, 2):
            probe = TestProbe(systems[i])
            dd[i].replicator.tell(Get(key, ReadLocal()), probe.ref)
            try:
                got = probe.receive_one(1.0)
            except AssertionError:
                return False
            ok.append(isinstance(got, GetSuccess)
                      and isinstance(got.data, PNCounterMap)
                      and got.data.get("ent-1") == 5)
        return all(ok)
    await_condition(converged, max_time=10.0)


def test_replicator_gossip_size_histograms():
    """Satellite observability: `ddata_gossip_payload_bytes` and
    `ddata_delta_vs_full` record per propagation tick when the metrics
    plane is enabled, and the per-key ratio evidences O(entry) deltas."""
    cfg = {"akka": {"actor": {"provider": "cluster"},
                    "metrics": {"enabled": True},
                    "cluster": {"distributed-data": {
                        "gossip-interval": "0.2s",
                        "delta-propagation-interval": "0.05s",
                        "notify-subscribers-interval": "0.05s"}}}}
    InProcTransport.fault_injector.reset()
    systems = [ActorSystem.create(f"ddm{i}", cfg) for i in range(2)]
    try:
        clusters = [Cluster.get(s) for s in systems]
        first = str(systems[0].provider.local_address)
        for c in clusters:
            c.join(first)
        await_condition(
            lambda: all(len([m for m in c.state.members
                             if m.status.value == "Up"]) == 2
                        for c in clusters), max_time=10.0)
        dd = [DistributedData.get(s) for s in systems]
        me = _node_id(systems[0])
        key = Key("sized")
        p = TestProbe(systems[0])
        # a wide map, then narrow updates: the ratio histogram must see
        # the O(entry) deltas, not O(map) snapshots
        dd[0].replicator.tell(
            Update(key, PNCounterMap.empty(), WriteLocal(),
                   modify=lambda m: _bulk_fill(m, me, 64)), p.ref)
        p.expect_msg_class(UpdateSuccess, 5.0)
        time.sleep(0.3)  # first tick flushes the bulk fill
        for i in range(3):
            dd[0].replicator.tell(
                Update(key, PNCounterMap.empty(), WriteLocal(),
                       modify=lambda m: m.increment(me, "k1", 1)), p.ref)
            p.expect_msg_class(UpdateSuccess, 5.0)
            time.sleep(0.2)
        reg = systems[0].metrics_registry
        snap = reg.snapshot()
        sizes = snap["histograms"]["ddata_gossip_payload_bytes"]
        ratios = snap["histograms"]["ddata_delta_vs_full"]
        assert sizes["count"] >= 2 and sizes["p50"] > 0
        assert ratios["count"] >= 1
        # at least one tick carried a narrow op: far below full-state size
        assert ratios["p50"] <= 0.5, ratios
    finally:
        for s in systems:
            s.terminate()
        for s in systems:
            s.await_termination(10.0)
        InProcTransport.fault_injector.reset()


def _bulk_fill(m, node, n):
    for i in range(n):
        m = m.increment(node, f"k{i}", 1)
    return m
