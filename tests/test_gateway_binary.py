"""Binary ingress (serialization/frames.py + gateway/ingress.py, ISSUE 11):
fixed-schema frame codec, batch decode, the columnar serve path, and the
equivalence contract against the JSON fallback.

Tier-1 scope: everything here is hostside or rides the module-scoped
region (the same spec shape as test_gateway's, so the in-process jit
cache is already warm); shapes stay <= 64 rows (the pow2-floor-64 scatter
padding means no new XLA compiles)."""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

from akka_tpu.gateway import (AdmissionController, GatewayServer,
                              RegionBackend, SloTracker, counter_behavior)
from akka_tpu.gateway.ingress import DEFAULT_MAX_FRAME, encode_body
from akka_tpu.serialization import frames


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _fresh_region():
    from akka_tpu.sharding.device import DeviceEntity, DeviceShardRegion
    spec = DeviceEntity("gwb", counter_behavior(4), n_shards=2,
                        entities_per_shard=8, n_devices=2, payload_width=4)
    return DeviceShardRegion(spec)


@pytest.fixture(scope="module")
def small_region():
    # shared across the file: 16 entity slots total (8/shard, hash-
    # assigned) — tests that spawn several fresh entity ids should build
    # their own region via _fresh_region() (same spec shape, so the jit
    # cache stays warm) instead of eating shared capacity
    return _fresh_region()


def _server(backend, rate=1e6, burst=1e6, clock=None, registry=None):
    adm = AdmissionController(rate=rate, burst=burst,
                              **({"clock": clock} if clock else {}))
    return GatewayServer(None, backend, adm, SloTracker(registry=registry),
                         registry=registry)


# ----------------------------------------------------------------- frame codec
def test_request_batch_roundtrip():
    body = frames.encode_request_batch(
        ids=[1, 2, 3], tenants=["t0", "t1", "t0"],
        entities=["a", "bb", "ccc"], ops=["add", "get", 1],
        values=[1.5, 0.0, -2.25])
    rec = frames.decode_request_batch(body)
    assert len(rec) == 3
    assert rec["id"].tolist() == [1, 2, 3]
    assert rec["op"].tolist() == [frames.OP_ADD, frames.OP_GET, frames.OP_ADD]
    assert rec["tenant"].tolist() == [b"t0", b"t1", b"t0"]
    assert rec["entity"].tolist() == [b"a", b"bb", b"ccc"]
    assert rec["value"].tolist() == [1.5, 0.0, -2.25]


def test_reply_batch_roundtrip_and_json_twin_dicts():
    body = frames.encode_reply_batch(
        np.asarray([7, 8, -1], np.int64),
        np.asarray([frames.ST_OK, frames.ST_SHED, frames.ST_ERROR], np.uint8),
        np.asarray([b"", b"rate_limited", b"timeout"]),
        np.asarray([42.5, 0.0, 0.0]),
        np.asarray([0, 120, 0], np.uint32))
    ok, shed, err = frames.decode_replies(body)
    # key sets per status match the JSON protocol exactly
    assert ok == {"id": 7, "status": "ok", "value": 42.5}
    assert shed == {"id": 8, "status": "shed", "reason": "rate_limited",
                    "retry_after_ms": 120}
    assert err == {"id": -1, "status": "error", "reason": "timeout"}


def test_frame_sniffing_disjoint_first_bytes():
    bin_body = frames.encode_request_batch([1], ["t"], ["e"], ["get"], [0.0])
    json_body = encode_body({"id": 1, "tenant": "t", "entity": "e",
                             "op": "get"})
    assert frames.is_binary(bin_body)
    assert not frames.is_binary(json_body)
    assert bin_body[0] == 0xAB and json_body[0] == ord("{")


def test_malformed_frames_typed_codes():
    good = frames.encode_request_batch([1], ["t"], ["e"], ["get"], [0.0])

    def code_of(body, **kw):
        with pytest.raises(frames.FrameFormatError) as ei:
            frames.decode_request_batch(body, **kw)
        return ei.value.code

    assert code_of(b"\xab\x01") == "truncated_header"
    assert code_of(b"\xff" + good[1:]) == "bad_magic"
    assert code_of(bytes([0xAB, 99]) + good[2:]) == "unsupported_version"
    assert code_of(good[:-1]) == "bad_length"
    assert code_of(good + b"x") == "bad_length"
    assert code_of(good, max_frame=8) == "oversize"
    # count=0 with a consistent length is still refused
    empty = frames._header(frames.KIND_REQUEST, 0)
    assert code_of(empty) == "empty_batch"
    # a reply body fed to the request decoder is typed, not mis-decoded
    reply = frames.encode_reply_batch(
        np.asarray([1], np.int64), np.asarray([0], np.uint8),
        np.asarray([b""]), np.zeros(1), np.zeros(1, np.uint32))
    assert code_of(reply) == "wrong_kind"


def test_string_too_long_is_typed_at_encode_time():
    with pytest.raises(frames.FrameFormatError, match="tenant_too_long"):
        frames.encode_request_batch([1], ["t" * 17], ["e"], ["get"], [0.0])
    with pytest.raises(frames.FrameFormatError, match="entity_too_long"):
        frames.encode_request_batch([1], ["t"], ["e" * 25], ["get"], [0.0])


def test_server_malformed_binary_replies_typed_and_keeps_serving():
    """Every malformed-binary shape surfaces as a one-record
    bad_frame:<code> reply (the JSON path's bad_request twin) and the
    server keeps serving afterwards — no admission charge, no SLO count,
    backend untouched."""
    class NeverBackend:
        def ask(self, entity_id, value):
            raise AssertionError("backend must not see a malformed frame")

    srv = _server(NeverBackend())
    good = frames.encode_request_batch([1], ["t"], ["e"], ["get"], [0.0])
    for body, code in [(b"\xab\x01", "truncated_header"),
                       (bytes([0xAB, 99]) + good[2:], "unsupported_version"),
                       (good[:-1], "bad_length"),
                       (frames._header(frames.KIND_REQUEST, 0),
                        "empty_batch")]:
        rep = frames.decode_replies(srv.handle_frame(body))
        assert rep == [{"id": -1, "status": "error",
                        "reason": f"bad_frame:{code}"}]
    assert srv.admission.admitted == 0
    assert srv.slo.artifact()["requests"] == 0
    # still serving: a well-formed frame after the garbage works
    class OkBackend:
        def ask(self, entity_id, value):
            return 5.0
    srv.backend = OkBackend()
    rep = frames.decode_replies(srv.handle_frame(good))
    assert rep == [{"id": 1, "status": "ok", "value": 5.0}]


def test_binary_admin_is_typed_error():
    srv = _server(None)
    body = frames.encode_request_batch([1], ["__admin"], ["e"], ["get"],
                                       [0.0])
    rep = frames.decode_replies(srv.handle_frame(body))[0]
    assert rep["status"] == "error"
    assert rep["reason"] == "bad_request:admin_requires_json"
    assert srv.slo.artifact()["requests"] == 0  # admin bypasses SLO, like JSON


# ------------------------------------------------------- frame-size unification
def test_one_frame_limit_at_both_ends():
    """Satellite: the client's FrameReader and the server's framing used
    to disagree (1<<20 vs 1<<16); now ONE default is shared by frames,
    ingress, FrameReader, GatewayServer and GatewayClient."""
    from akka_tpu.gateway.ingress import FrameReader, GatewayClient
    assert DEFAULT_MAX_FRAME == frames.DEFAULT_MAX_FRAME == 1 << 20
    assert FrameReader().max_frame == DEFAULT_MAX_FRAME
    assert GatewayServer(None, None, None, None).max_frame \
        == DEFAULT_MAX_FRAME
    assert GatewayClient("h", 1).max_frame == DEFAULT_MAX_FRAME
    # a frame above the OLD client limit (1<<16) now reassembles fine
    big = {"id": 1, "status": "ok", "value": "x" * (1 << 17)}
    blob = frames.frame(encode_body(big))
    out = list(FrameReader().feed(blob))
    assert out == [big]


# ------------------------------------------------------------ vectorized parity
def test_acquire_upto_matches_sequential_try_acquire():
    from akka_tpu.gateway import TokenBucket
    for rate, burst, taken, n in [(10.0, 3.0, 0, 5), (10.0, 3.0, 2, 5),
                                  (0.0, 4.0, 0, 2), (5.0, 2.5, 0, 3)]:
        ca, cb = FakeClock(), FakeClock()
        a = TokenBucket(rate=rate, burst=burst, clock=ca)
        b = TokenBucket(rate=rate, burst=burst, clock=cb)
        for _ in range(taken):
            a.try_acquire(), b.try_acquire()
        ca.advance(0.05), cb.advance(0.05)
        k = a.acquire_upto(n)
        seq = sum(b.try_acquire() for _ in range(n))
        assert k == seq, (rate, burst, taken, n)


def test_admit_batch_matches_sequential_admits():
    clk1, clk2 = FakeClock(), FakeClock()
    a1 = AdmissionController(rate=0.0, burst=3.0, clock=clk1)
    a2 = AdmissionController(rate=0.0, burst=3.0, clock=clk2)
    k, rej = a1.admit_batch("t0", 5)
    assert k == 3 and rej is not None and rej.reason == "rate_limited"
    assert rej.retry_after_s > 0
    seq = [a2.admit("t0") for _ in range(5)]
    assert sum(r is None for r in seq) == k
    assert a1.admitted == a2.admitted == 3
    assert a1.rejected_by_reason == a2.rejected_by_reason \
        == {"rate_limited": 2}
    # overload sheds the whole window with the typed overloaded reason
    sig = {"v": 2.0}
    a3 = AdmissionController(rate=1e9, burst=1e9,
                             pressure_signals={"boom": lambda: sig["v"]},
                             thresholds={"boom": 1.0},
                             check_interval_s=0.0, clock=FakeClock())
    k, rej = a3.admit_batch("t0", 4)
    assert k == 0 and rej.reason == "overloaded:boom"
    assert a3.rejected_by_reason == {"overloaded:boom": 4}


def test_histogram_observe_many_matches_scalar_observe():
    from akka_tpu.event.metrics import Histogram
    vals = [0.0, 0.3, 1.0, 1.7, 2.0, 3.9, 4.0, 100.0, 1e6, 1e12]
    a, b = Histogram("a"), Histogram("b")
    a.observe_many(vals, step=7)
    for v in vals:
        b.observe(v, step=7)
    assert a._buckets.tolist() == b._buckets.tolist()
    assert a.snapshot() == b.snapshot()


def test_slo_record_many_matches_scalar_record():
    a, b = SloTracker(), SloTracker()
    outs = ["ok", "ok", "reject", "timeout", "error", "ok"]
    lats = [0.01, 0.02, None, 5.0, 0.03, 0.04]
    a.record_many("t0", outs, lats)
    for o, s in zip(outs, lats):
        b.record("t0", o, s)
    assert a.artifact() == b.artifact()
    with pytest.raises(ValueError):
        a.record_many("t0", ["dropped"])


# -------------------------------------------------------- JSON <-> binary twins
def _json_req(srv, rid, tenant, entity, op, value):
    req = {"id": rid, "tenant": tenant, "op": op, "value": value}
    if entity is not None:
        req["entity"] = entity
    return json.loads(srv.handle_frame(encode_body(req)))


def _bin_req(srv, rid, tenant, entity, op, value):
    body = frames.encode_request_batch(
        [rid], [tenant], ["" if entity is None else entity],
        [op if isinstance(op, int) else frames.OP_CODES.get(op, op)],
        [value])
    return frames.decode_replies(srv.handle_frame(body))[0]


def _strip_latency(art):
    for k in ("p50_ms", "p99_ms", "p50_met", "p99_met"):
        art.pop(k)
    return art


def test_binary_json_equivalence_property(small_region):
    """THE equivalence contract: the same mixed request sequence through
    two fresh servers — one JSON, one binary — produces identical decoded
    reply dicts, identical SLO counters and identical admission counters.
    Sequence covers ok adds/gets, missing entity (typed before admission),
    unknown op (typed after admission, charged) and rate-limit sheds."""
    mk = lambda: _server(RegionBackend(small_region), rate=0.0, burst=6.0,
                         clock=FakeClock())
    srv_j, srv_b = mk(), mk()
    # entity namespaces disjoint so both sides start from zero totals
    seq = [("t0", "{}-a", "add", 1.5), ("t0", "{}-a", "add", 2.0),
           ("t0", None, "add", 9.0),          # missing entity: not charged
           ("t0", "{}-b", "add", 4.0), ("t1", "{}-a", "get", 0.0),
           ("t0", "{}-a", 7, 0.0),            # unknown op: charged
           ("t0", "{}-a", "get", 0.0), ("t0", "{}-b", "get", 0.0),
           ("t0", "{}-a", "add", 1.0),        # bucket empty -> shed
           ("t1", "{}-a", "add", 3.0)]
    reps_j = [_json_req(srv_j, i, t, e and e.format("eqj"),
                        "7" if op == 7 else op, v)
              for i, (t, e, op, v) in enumerate(seq)]
    reps_b = [_bin_req(srv_b, i, t, e and e.format("eqb"), op, v)
              for i, (t, e, op, v) in enumerate(seq)]
    assert reps_j == reps_b
    assert [r["status"] for r in reps_j] == \
        ["ok", "ok", "error", "ok", "ok", "error", "ok", "ok", "shed", "ok"]
    assert _strip_latency(srv_j.slo.artifact()) == \
        _strip_latency(srv_b.slo.artifact())
    for a in (srv_j.admission, srv_b.admission):
        # t0: 7 charges (unknown-op charged, missing-entity NOT) vs
        # burst 6 -> 6 admitted + 1 shed; t1: 2 admitted
        assert a.admitted == 8
        assert a.rejected_by_reason == {"rate_limited": 1}
    # and the windowed form of the same sequence lands the same counters
    srv_w = mk()
    body = frames.encode_request_batch(
        list(range(len(seq))), [t for t, *_ in seq],
        [(e and e.format("eqw")) or "" for _, e, *_ in seq],
        [op if isinstance(op, int) else frames.OP_CODES.get(op, op)
         for *_, op, _ in seq],
        [v for *_, v in seq])
    reps_w = frames.decode_replies(srv_w.handle_frame(body))
    assert reps_w == reps_j
    assert _strip_latency(srv_w.slo.artifact()) == \
        _strip_latency(srv_j.slo.artifact())
    assert srv_w.admission.admitted == 8
    assert srv_w.admission.rejected_by_reason == {"rate_limited": 1}


def test_traced_replies_id_parity_both_encodings():
    """ISSUE 12 satellite: with tracing on (100% sampled), EVERY reply —
    ok, typed error, shed — carries its trace id on BOTH encodings, the
    reply dicts stay twins modulo the trace values themselves (each
    server mints its own id stream), and every reply's trace id resolves
    in that server's span store to a gw.request root with the MATCHING
    request id — the client-report -> server-trace join the satellite
    exists for.

    Own region: four fresh entity ids would eat half a shard of the
    module-shared region's capacity."""
    from akka_tpu.event.tracing import Tracer
    region = _fresh_region()

    def mk():
        tr = Tracer(sample_rate=1.0, seed=11)
        adm = AdmissionController(rate=0.0, burst=3.0, clock=FakeClock())
        srv = GatewayServer(None, RegionBackend(region), adm,
                            SloTracker(), tracer=tr)
        return srv, tr

    seq = [("t0", "{}-a", "add", 1.0),
           ("t0", None, "add", 2.0),    # missing entity: typed error
           ("t0", "{}-a", "get", 0.0), ("t0", "{}-b", "add", 4.0),
           ("t0", "{}-a", "add", 1.0)]  # bucket (burst 3) empty: shed
    srv_j, tr_j = mk()
    srv_b, tr_b = mk()
    reps_j = [_json_req(srv_j, i, t, e and e.format("trj"), op, v)
              for i, (t, e, op, v) in enumerate(seq)]
    reps_b = [_bin_req(srv_b, i, t, e and e.format("trb"), op, v)
              for i, (t, e, op, v) in enumerate(seq)]
    assert [r["status"] for r in reps_j] == \
        [r["status"] for r in reps_b] == \
        ["ok", "error", "ok", "ok", "shed"]
    strip = lambda r: {k: v for k, v in r.items() if k != "trace"}
    assert [strip(r) for r in reps_j] == [strip(r) for r in reps_b]
    for reps, tr in ((reps_j, tr_j), (reps_b, tr_b)):
        assert all(r.get("trace") for r in reps)  # ok AND error AND shed
        roots = {s["trace"]: s for s in tr.of_name("gw.request")}
        for i, r in enumerate(reps):
            assert roots[r["trace"]]["id"] == i  # id parity, per reply


def test_malformed_frames_traced_on_both_encodings(small_region):
    """A frame that dies before a request id even exists still gets an
    anonymous trace: the typed reply carries it and the matching
    gw.bad_request / gw.bad_frame span is in the store."""
    from akka_tpu.event.tracing import Tracer
    tr = Tracer(sample_rate=1.0, seed=2)
    srv = _server(RegionBackend(small_region))
    srv._tracer = tr
    rep = json.loads(srv.handle_frame(b"{not json"))
    assert rep["status"] == "error" and rep["trace"]
    assert tr.of_name("gw.bad_request")[0]["trace"] == rep["trace"]
    rep_b = frames.decode_replies(srv.handle_frame(b"\xab\x01\x00"))[0]
    assert rep_b["reason"] == "bad_frame:truncated_header"
    assert rep_b["trace"]
    assert tr.of_name("gw.bad_frame")[0]["trace"] == rep_b["trace"]


def test_untraced_replies_have_no_trace_key():
    """Tracing off (no tracer): byte-identical version-1 replies, no
    "trace" key on either encoding — the pre-ISSUE-12 wire, untouched.

    Own region (two fresh entity ids; see small_region's capacity note)."""
    srv = _server(RegionBackend(_fresh_region()))
    j = _json_req(srv, 3, "tw", "nt-a", "add", 1.0)
    body = frames.encode_request_batch([3], ["tw"], ["nt-b"],
                                       [frames.OP_ADD], [1.0])
    out = srv.handle_frame(body)
    assert out[1] == frames.VERSION  # version-1 reply bytes
    b = frames.decode_replies(out)[0]
    assert "trace" not in j and "trace" not in b
    assert "trace" not in frames.decode_reply_batch(out).dtype.names


def test_solo_binary_is_json_twin(small_region):
    srv = _server(RegionBackend(small_region))
    j = _json_req(srv, 1, "tw", "twin-j", "add", 2.5)
    b = _bin_req(srv, 1, "tw", "twin-b", "add", 2.5)
    assert j == b == {"id": 1, "status": "ok", "value": 2.5}
    assert _json_req(srv, 2, "tw", "twin-j", "get", 0.0)["value"] == \
        _bin_req(srv, 2, "tw", "twin-b", "get", 0.0)["value"] == 2.5


def test_window_linearizes_same_entity_adds(small_region):
    """Two adds to ONE entity inside one window serialize (the ask-wave
    one-in-flight-per-row rule): replies are the running totals and the
    final get observes both."""
    srv = _server(RegionBackend(small_region))
    body = frames.encode_request_batch(
        [1, 2, 3], ["t0"] * 3, ["lin-a"] * 3,
        [frames.OP_ADD, frames.OP_ADD, frames.OP_GET], [1.0, 2.0, 0.0])
    reps = frames.decode_replies(srv.handle_frame(body))
    assert [r["value"] for r in reps] == [1.0, 3.0, 3.0]


def test_handle_frame_batch_merges_and_aligns(small_region):
    """In-proc window entry point: contiguous binary frames merge into
    one decode + one wave; JSON frames and per-frame decode errors stay
    isolated; replies align 1:1 with the inputs."""
    srv = _server(RegionBackend(small_region))
    b1 = frames.encode_request_batch([1, 2], ["t0"] * 2, ["hfb-a", "hfb-b"],
                                     [frames.OP_ADD] * 2, [1.0, 2.0])
    b2 = frames.encode_request_batch([3], ["t0"], ["hfb-a"],
                                     [frames.OP_GET], [0.0])
    js = encode_body({"id": 4, "tenant": "t0", "entity": "hfb-b",
                      "op": "get"})
    bad = b"\xab\x01"
    outs = srv.handle_frame_batch([b1, bad, b2, js])
    r1 = frames.decode_replies(outs[0])
    assert [r["value"] for r in r1] == [1.0, 2.0]
    assert frames.decode_replies(outs[1])[0]["reason"] == \
        "bad_frame:truncated_header"
    assert frames.decode_replies(outs[2])[0]["value"] == 1.0
    assert json.loads(outs[3]) == {"id": 4, "status": "ok", "value": 2.0}


def test_handle_frame_batch_merges_noncontiguous_binaries():
    """ISSUE 13 satellite: binary frames separated by JSON frames in one
    window still merge into a SINGLE frombuffer decode (one histogram
    observe covering every binary record), and the JSON frame rides the
    same serve pass — one admission poll, aligned replies."""
    from akka_tpu.event.metrics import MetricsRegistry

    class OkBackend:
        def ask(self, entity_id, value):
            return 7.0 + value

    reg = MetricsRegistry()
    reg.set_step(9)
    srv = _server(OkBackend(), registry=reg)
    b1 = frames.encode_request_batch([1, 2], ["t0"] * 2, ["nc-a", "nc-b"],
                                     [frames.OP_ADD] * 2, [1.0, 2.0])
    js = encode_body({"id": 3, "tenant": "t0", "entity": "nc-c",
                      "op": "get"})
    b2 = frames.encode_request_batch([4], ["t0"], ["nc-d"],
                                     [frames.OP_GET], [0.0])
    outs = srv.handle_frame_batch([b1, js, b2])
    assert [r["value"] for r in frames.decode_replies(outs[0])] \
        == [8.0, 9.0]
    assert json.loads(outs[1]) == {"id": 3, "status": "ok", "value": 7.0}
    assert frames.decode_replies(outs[2])[0]["value"] == 7.0
    size = reg.histogram("gateway_decode_batch_size").snapshot()
    assert size["count"] == 1 and size["sum"] == 3.0 and size["step"] == 9


# -------------------------------------------------------------- decode metrics
def test_decode_metrics_histograms_step_stamped():
    from akka_tpu.event.metrics import MetricsRegistry

    class OkBackend:
        def ask(self, entity_id, value):
            return 1.0

    reg = MetricsRegistry()
    reg.set_step(42)
    srv = _server(OkBackend(), registry=reg)
    body = frames.encode_request_batch(
        list(range(5)), ["t0"] * 5, [f"m-{i}" for i in range(5)],
        [frames.OP_ADD] * 5, [1.0] * 5)
    srv.handle_frame(body)
    size = reg.histogram("gateway_decode_batch_size").snapshot()
    ns = reg.histogram("gateway_decode_ns_per_frame").snapshot()
    assert size["count"] == 1 and size["sum"] == 5.0 and size["step"] == 42
    assert ns["count"] == 1 and ns["sum"] > 0 and ns["step"] == 42


# -------------------------------------------------------- decode throughput
def test_binary_batch_decode_beats_json_decode_3x():
    """Tier-1 smoke budget (ISSUE 11 acceptance): batch-decoding a binary
    window is >= 3x faster than json.loads over the same requests. Small
    fixed shape (512 records), best-of-5 to dodge scheduler noise."""
    n = 512
    bin_body = frames.encode_request_batch(
        list(range(n)), [f"t{i % 8}" for i in range(n)],
        [f"acct-{i % 64}" for i in range(n)],
        [frames.OP_ADD] * n, [float(i) for i in range(n)])
    json_bodies = [encode_body({"id": i, "tenant": f"t{i % 8}",
                                "entity": f"acct-{i % 64}", "op": "add",
                                "value": float(i)}) for i in range(n)]

    def best_of(f, reps=5):
        t = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            t.append(time.perf_counter() - t0)
        return min(t)

    tb = best_of(lambda: frames.decode_request_batch(bin_body))
    tj = best_of(lambda: [json.loads(b) for b in json_bodies])
    rec = frames.decode_request_batch(bin_body)
    assert len(rec) == n
    assert tj / tb >= 3.0, f"binary {tb * 1e6:.1f}us vs json {tj * 1e6:.1f}us"
