"""Behavior tests for the fourth operator tranche (VERDICT r3 #5) —
modeled on the reference operator specs: FlowStatefulMapSpec,
FlowMapWithResourceSpec, FlowMapAsyncPartitionedSpec, FlowGroupedWeightedSpec,
FlowDelaySpec, FlowMonitorSpec, FlowWatchSpec, SourceSpec (maybe/unfoldAsync/
zipWithN), LazySinkSpec, FlowSwitchMapSpec."""

import time
from concurrent.futures import Future, ThreadPoolExecutor

import pytest

from akka_tpu import ActorSystem
from akka_tpu.stream import Flow, Keep, Sink, Source

CFG = {"akka": {"stdout-loglevel": "OFF", "log-dead-letters": 0}}
POOL = ThreadPoolExecutor(4)


@pytest.fixture(scope="module")
def system():
    s = ActorSystem.create("stream-ops4-test", CFG)
    yield s
    s.terminate()
    s.await_termination(10.0)


def run_seq(source, system, timeout=5.0):
    return source.run_with(Sink.seq(), system).result(timeout)


def later(v, delay=0.01):
    def work():
        time.sleep(delay)
        return v
    return POOL.submit(work)


# -- stateful element ops -----------------------------------------------------

def test_stateful_map(system):
    out = run_seq(
        Source.from_iterable([1, 2, 3, 4]).stateful_map(
            lambda: 0,
            lambda s, x: (s + x, s + x),          # running sum
            on_complete=lambda s: ("total", s)),
        system)
    assert out == [1, 3, 6, 10, ("total", 10)]


def test_stateful_map_fresh_state_per_materialization(system):
    src = Source.from_iterable([1, 1]).stateful_map(
        lambda: 0, lambda s, x: (s + x, s + x))
    assert run_seq(src, system) == [1, 2]
    assert run_seq(src, system) == [1, 2]


def test_map_with_resource(system):
    closed = []

    def close(r):
        closed.append(r["n"])
        return ("closed", r["n"])

    out = run_seq(
        Source.from_iterable([1, 2, 3]).map_with_resource(
            lambda: {"n": 0},
            lambda r, x: (r.__setitem__("n", r["n"] + 1), x * 10)[1],
            close),
        system)
    assert out == [10, 20, 30, ("closed", 3)]
    assert closed == [3]


def test_map_with_resource_closes_on_cancel(system):
    closed = []
    out = run_seq(
        Source.from_iterable(range(100)).map_with_resource(
            lambda: "res", lambda r, x: x, lambda r: closed.append(r))
        .take(2),
        system)
    assert out == [0, 1]
    assert closed == ["res"]


def test_map_async_partitioned_orders_and_serializes_partitions(system):
    in_flight = {}
    max_concurrent_per_part = {}

    def fn(elem, part):
        def work():
            in_flight[part] = in_flight.get(part, 0) + 1
            max_concurrent_per_part[part] = max(
                max_concurrent_per_part.get(part, 0), in_flight[part])
            time.sleep(0.01)
            in_flight[part] = in_flight[part] - 1
            return elem * 10
        return POOL.submit(work)

    out = run_seq(
        Source.from_iterable(range(12)).map_async_partitioned(
            4, lambda x: x % 3, fn),
        system, timeout=10.0)
    assert out == [x * 10 for x in range(12)]  # input order preserved
    assert all(v == 1 for v in max_concurrent_per_part.values())


# -- weighted grouping --------------------------------------------------------

def test_grouped_weighted(system):
    out = run_seq(
        Source.from_iterable([1, 2, 3, 4, 5]).grouped_weighted(
            3, lambda x: x),
        system)
    assert out == [[1, 2], [3], [4], [5]]


def test_grouped_weighted_within_flushes_on_window(system):
    out = run_seq(
        Source.tick(0.01, 0.03, "t").take(3)
        .grouped_weighted_within(100, 0.05, lambda x: 1),
        system, timeout=10.0)
    assert sum(len(g) for g in out) == 3
    assert len(out) >= 2  # the window fired at least once mid-stream


def test_batch_weighted(system):
    # fast producer, slow consumer: batches aggregate by weight
    out = run_seq(
        Source.from_iterable(range(10)).batch_weighted(
            100, lambda x: 1, lambda x: [x], lambda acc, x: acc + [x])
        .delay(0.02),
        system, timeout=10.0)
    flat = [x for g in out for x in g]
    assert flat == list(range(10))


# -- timer ops ----------------------------------------------------------------

def test_initial_delay(system):
    t0 = time.monotonic()
    out = run_seq(Source.from_iterable([1, 2, 3]).initial_delay(0.1), system)
    assert out == [1, 2, 3]
    assert time.monotonic() - t0 >= 0.09


def test_backpressure_timeout_passes_fast_consumer(system):
    out = run_seq(
        Source.from_iterable(range(5)).backpressure_timeout(5.0), system)
    assert out == list(range(5))


def test_backpressure_timeout_fails_stuck_consumer(system):
    from akka_tpu.stream.ops4 import BackpressureTimeoutException
    fut = Source.from_iterable(range(5)) \
        .backpressure_timeout(0.05) \
        .map_async(1, lambda x: later(x, delay=10.0) if x else x) \
        .run_with(Sink.seq(), system)
    assert isinstance(fut.exception(10.0), BackpressureTimeoutException)


def test_delay_with(system):
    t0 = time.monotonic()
    out = run_seq(
        Source.from_iterable([1, 2]).delay_with(
            lambda: (lambda elem: 0.05 * elem)),
        system, timeout=10.0)
    assert out == [1, 2]
    assert time.monotonic() - t0 >= 0.1  # 0.05 + staggered 0.1


# -- monitor / foldWhile / watch / detach ------------------------------------

def test_monitor(system):
    mon_holder = {}
    out = (Source.from_iterable([1, 2, 3])
           .via_mat(Flow().monitor().map_materialized_value(
               lambda m: mon_holder.setdefault("m", m)), Keep.right)
           .run_with(Sink.seq(), system))
    assert out.result(5.0) == [1, 2, 3]
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and \
            mon_holder["m"].state[0] != "finished":
        time.sleep(0.01)
    assert mon_holder["m"].state == ("finished",)


def test_fold_while(system):
    # sum until the aggregate reaches 10; upstream is infinite
    out = run_seq(
        Source.repeat(3).fold_while(0, lambda acc: acc < 10,
                                    lambda acc, x: acc + x),
        system)
    assert out == [12]


def test_watch_fails_stream_when_actor_dies(system):
    from akka_tpu.actor.props import Props
    from akka_tpu.stream.ops4 import WatchedActorTerminatedException

    ref = system.actor_of(Props.from_receive(lambda ctx, msg: None))
    fut = Source.tick(0.01, 0.05, "x").watch(ref) \
        .run_with(Sink.seq(), system)
    time.sleep(0.1)
    system.stop(ref)
    assert isinstance(fut.exception(10.0), WatchedActorTerminatedException)


def test_detach_passes_elements(system):
    assert run_seq(Source.from_iterable(range(6)).detach(), system) \
        == list(range(6))


# -- compositional tail -------------------------------------------------------

def test_recover_with(system):
    out = run_seq(
        Source.from_iterable([1, 2]).concat(Source.failed(ValueError("x")))
        .recover_with(lambda ex: Source.from_iterable([8, 9])),
        system)
    assert out == [1, 2, 8, 9]


def test_collect_first_and_collect_while(system):
    out = run_seq(
        Source.from_iterable([1, 3, 4, 5, 6]).collect_first(
            lambda x: x * 10 if x % 2 == 0 else None),
        system)
    assert out == [40]
    out = run_seq(
        Source.from_iterable([2, 4, 5, 6]).collect_while(
            lambda x: x * 10 if x % 2 == 0 else None),
        system)
    assert out == [20, 40]


def test_flatten_merge(system):
    out = run_seq(
        Source.from_iterable([Source.from_iterable([1, 2]),
                              Source.from_iterable([3, 4])])
        .flatten_merge(2),
        system)
    assert sorted(out) == [1, 2, 3, 4]


def test_switch_map_cancels_previous_inner(system):
    # a new outer element switches away from the (infinite) previous inner
    out = run_seq(
        Source.from_iterable(["a", "b"])
        .switch_map(lambda k: Source.tick(0.0, 0.01, k).take(50)
                    if k == "a" else Source.from_iterable([k] * 3)),
        system, timeout=10.0)
    assert out[-3:] == ["b", "b", "b"]
    assert len(out) < 53  # "a" was cut short by the switch


def test_concat_lazy_and_prepend_lazy(system):
    built = []

    def make_second():
        built.append(True)
        return Source.from_iterable([3, 4])

    src = Source.from_iterable([1, 2]).concat_lazy(
        Source.lazy_source(make_second))
    assert built == []  # not built before materialization+pull
    assert run_seq(src, system) == [1, 2, 3, 4]
    assert run_seq(
        Source.from_iterable([3, 4]).prepend_lazy(Source.from_iterable([1])),
        system) == [1, 3, 4]


def test_map_materialized_value(system):
    fut = Source.from_iterable([1, 2]) \
        .map_materialized_value(lambda m: ("wrapped", m)) \
        .run_with(Sink.seq(), system)
    assert fut.result(5.0) == [1, 2]


# -- async sources ------------------------------------------------------------

def test_source_maybe_success(system):
    src = Source.maybe()
    from akka_tpu.stream import Materializer
    pair = src.to_mat(Sink.seq(), Keep.both).run(Materializer(system))
    promise, fut = pair
    promise.success(42)
    assert fut.result(5.0) == [42]


def test_source_maybe_empty_and_failure(system):
    from akka_tpu.stream import Materializer
    promise, fut = Source.maybe().to_mat(Sink.seq(), Keep.both) \
        .run(Materializer(system))
    promise.success(None)
    assert fut.result(5.0) == []
    promise2, fut2 = Source.maybe().to_mat(Sink.seq(), Keep.both) \
        .run(Materializer(system))
    promise2.failure(RuntimeError("nope"))
    assert isinstance(fut2.exception(5.0), RuntimeError)


def test_unfold_async(system):
    def fn(s):
        if s >= 4:
            return later(None)
        return later((s + 1, s))
    assert run_seq(Source.unfold_async(0, fn), system, timeout=10.0) \
        == [0, 1, 2, 3]


def test_unfold_resource_async(system):
    closed = []

    def create():
        return later(iter([1, 2, 3]))

    def read(it):
        return later(next(it, None))

    def close(it):
        closed.append(True)
        return later(True)

    out = run_seq(Source.unfold_resource_async(create, read, close),
                  system, timeout=10.0)
    assert out == [1, 2, 3]
    assert closed == [True]


def test_zip_n_and_zip_with_n(system):
    out = run_seq(Source.zip_n([Source.from_iterable([1, 2, 3]),
                                Source.from_iterable("ab")]), system)
    assert out == [[1, "a"], [2, "b"]]
    out = run_seq(Source.zip_with_n(
        lambda xs: sum(xs), [Source.from_iterable([1, 2]),
                             Source.from_iterable([10, 20]),
                             Source.from_iterable([100, 200])]), system)
    assert out == [111, 222]


def test_merge_latest(system):
    out = run_seq(
        Source.from_iterable([1]).merge_latest(
            Source.from_iterable(["a", "b"])),
        system)
    # after both sides emitted, each update emits the latest pair
    assert [1, "a"] in out or [1, "b"] in out
    assert out[-1] == [1, "b"]


def test_merge_prioritized_n(system):
    out = run_seq(Source.merge_prioritized_n(
        [(Source.from_iterable([1, 1]), 1),
         (Source.from_iterable([9, 9]), 10)]), system)
    assert sorted(out) == [1, 1, 9, 9]


def test_source_range_and_from_iterator(system):
    assert run_seq(Source.range(1, 5), system) == [1, 2, 3, 4, 5]
    assert run_seq(Source.range(5, 1, -2), system) == [5, 3, 1]
    calls = []

    def factory():
        calls.append(True)
        return iter([1, 2])
    src = Source.from_iterator(factory)
    assert run_seq(src, system) == [1, 2]
    assert run_seq(src, system) == [1, 2]  # fresh iterator per run
    assert len(calls) == 2


def test_actor_ref_with_backpressure(system):
    from akka_tpu.actor.actor import Actor
    from akka_tpu.actor.messages import Status
    from akka_tpu.actor.props import Props
    from akka_tpu.stream import Materializer

    ref_fut, seq_fut = Source.actor_ref_with_backpressure("ACK") \
        .to_mat(Sink.seq(), Keep.both).run(Materializer(system))
    ref = ref_fut.result(5.0)

    acks = []

    class Producer(Actor):
        def pre_start(self):
            ref.tell("one", self.self_ref)

        def receive(self, message):
            if message == "ACK":
                acks.append(True)
                if len(acks) == 1:
                    ref.tell("two", self.self_ref)
                else:
                    ref.tell(Status.Success(None), self.self_ref)

    system.actor_of(Props.create(Producer))
    assert seq_fut.result(5.0) == ["one", "two"]
    assert len(acks) == 2


# -- sinks --------------------------------------------------------------------

def test_foreach_async(system):
    seen = []

    def fn(x):
        return later(seen.append(x))
    fut = Source.from_iterable([1, 2, 3]).run_with(
        Sink.foreach_async(2, fn), system)
    fut.result(10.0)
    assert sorted(seen) == [1, 2, 3]


def test_sink_cancelled(system):
    from akka_tpu.stream import Materializer
    Source.from_iterable(range(1000)).to(Sink.cancelled()) \
        .run(Materializer(system))
    # nothing to assert beyond termination: the stream cancels cleanly


def test_lazy_sink_builds_on_first_element(system):
    built, seen = [], []

    def factory():
        built.append(True)
        return Sink.foreach(seen.append)

    Source.from_iterable([1, 2, 3]).to(Sink.lazy_sink(factory)).run(system)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and len(seen) < 3:
        time.sleep(0.01)
    assert built == [True]
    assert seen == [1, 2, 3]


def test_lazy_sink_never_builds_without_elements(system):
    built = []

    def factory():
        built.append(True)
        return Sink.ignore()

    Source.empty().to(Sink.lazy_sink(factory)).run(system)
    time.sleep(0.2)
    assert built == []


def test_future_sink(system):
    seen = []
    fut: Future = Future()
    Source.from_iterable([1, 2]).to(Sink.future_sink(fut)).run(system)
    time.sleep(0.05)
    fut.set_result(Sink.foreach(seen.append))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and len(seen) < 2:
        time.sleep(0.01)
    assert seen == [1, 2]


# -- lazy flow / from_sink_and_source ----------------------------------------

def test_lazy_flow(system):
    built = []

    def factory():
        built.append(True)
        return Flow().map(lambda x: x * 2)

    out = run_seq(Source.from_iterable([1, 2, 3]).via(
        Flow.lazy_flow(factory)), system)
    assert out == [2, 4, 6]  # first element went through the inner flow too
    assert built == [True]


def test_from_sink_and_source(system):
    seen = []
    flow = Flow.from_sink_and_source(
        Sink.foreach(seen.append), Source.from_iterable(["x", "y"]))
    out = run_seq(Source.from_iterable([1, 2]).via(flow), system)
    assert out == ["x", "y"]
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and len(seen) < 2:
        time.sleep(0.01)
    assert seen == [1, 2]


def test_from_sink_and_source_coupled_cancels_input_side(system):
    # output side completes -> input side must be torn down too
    flow = Flow.from_sink_and_source_coupled(
        Sink.ignore(), Source.from_iterable(["x"]))
    out = run_seq(Source.tick(0.01, 0.01, 1).via(flow), system, timeout=10.0)
    assert out == ["x"]


def test_pre_materialize(system):
    from akka_tpu.stream import Materializer
    mat, src = Source.from_iterable([1, 2, 3]).pre_materialize(
        Materializer(system))
    assert run_seq(src, system) == [1, 2, 3]


# -- review-hardening cases ---------------------------------------------------

def test_map_async_partitioned_sync_fn(system):
    # fn returning plain values (allowed) must not corrupt the entry queue
    out = run_seq(
        Source.from_iterable(range(6)).map_async_partitioned(
            2, lambda e: e % 2, lambda e, p: e * 10),
        system)
    assert out == [0, 10, 20, 30, 40, 50]


def test_source_maybe_downstream_cancel_completes(system):
    out = run_seq(Source.maybe().take(0), system)
    assert out == []


def test_merge_latest_backpressure_bounded(system):
    # fast inputs + slow consumer: stream still completes, output bounded
    out = run_seq(
        Source.from_iterable(range(50)).merge_latest(
            Source.from_iterable(range(50))).take(5).delay(0.01),
        system, timeout=10.0)
    assert len(out) == 5


def test_lazy_sink_materializes_inner_mat(system):
    from akka_tpu.stream import Materializer
    fut = Source.from_iterable([1, 2, 3]).to_mat(
        Sink.lazy_sink(lambda: Sink.seq()), Keep.right) \
        .run(Materializer(system))
    inner_mat = fut.result(5.0)          # Future[inner Sink.seq future]
    assert inner_mat.result(5.0) == [1, 2, 3]


def test_lazy_sink_mat_fails_when_never_materialized(system):
    from akka_tpu.stream import Materializer
    from akka_tpu.stream.ops4 import NeverMaterializedException
    fut = Source.empty().to_mat(
        Sink.lazy_sink(lambda: Sink.seq()), Keep.right) \
        .run(Materializer(system))
    assert isinstance(fut.exception(5.0), NeverMaterializedException)


def test_actor_ref_with_backpressure_two_senders_no_loss(system):
    from akka_tpu.actor.actor import Actor
    from akka_tpu.actor.messages import Status
    from akka_tpu.actor.props import Props
    from akka_tpu.stream import Materializer

    ref_fut, seq_fut = Source.actor_ref_with_backpressure("ACK") \
        .to_mat(Sink.seq(), Keep.both).run(Materializer(system))
    ref = ref_fut.result(5.0)
    acked = []

    class P(Actor):
        def __init__(self, tag):
            super().__init__()
            self.tag = tag

        def pre_start(self):
            ref.tell(self.tag, self.self_ref)

        def receive(self, message):
            if message == "ACK":
                acked.append(self.tag)

    system.actor_of(Props.create(P, "a"))
    system.actor_of(Props.create(P, "b"))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and len(acked) < 2:
        time.sleep(0.01)
    assert sorted(acked) == ["a", "b"]   # neither sender lost its ack
    ref.tell(Status.Success(None), None)
    assert sorted(seq_fut.result(5.0)) == ["a", "b"]
