"""Test env: force CPU backend with a virtual 8-device mesh so multi-chip
sharding tests run anywhere (SURVEY.md §4 TPU translation: multi-node tests
on a simulated mesh via xla_force_host_platform_device_count)."""

import os

# force-override: the ambient env may preset JAX_PLATFORMS to a TPU platform
# (and a sitecustomize may have registered + selected it before conftest runs),
# so set both the env var and the live jax config
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running scale tests")
    config.addinivalue_line(
        "markers",
        "timing: wall-clock-coupled suites (lease TTLs, heartbeats, SBR "
        "stable-after). Deadlines auto-dilate with machine load "
        "(akka_tpu.testkit.dilation; override with "
        "AKKA_TPU_TEST_TIMEFACTOR). Run these WITHOUT pytest-xdist "
        "parallelism; they tolerate background load via dilation but "
        "sharing one core pool with other timing suites multiplies "
        "variance.")


def pytest_report_header(config):
    from akka_tpu.testkit.dilation import time_factor
    return (f"akka-tpu timing dilation: factor={time_factor():.2f} "
            f"(load={os.getloadavg()[0]:.1f}/{os.cpu_count()} cpus; "
            f"override: AKKA_TPU_TEST_TIMEFACTOR)")
