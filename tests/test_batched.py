"""BatchedSystem correctness vs a Python oracle (SURVEY.md §7 minimum slice:
compare the device dispatcher against the host reference for ring/fan-in)."""

import numpy as np
import pytest

import jax.numpy as jnp

from akka_tpu.batched import BatchedSystem, Ctx, Emit, Inbox, behavior


def ring_behavior(payload_width=4, out_degree=1):
    @behavior("ring", {"received": ((), jnp.int32), "last": ((), jnp.float32)})
    def ring(state, inbox, ctx):
        nxt = (ctx.actor_id + 1) % ctx.n_actors
        token = inbox.sum[0]
        new = {"received": state["received"] + inbox.count,
               "last": token.astype(jnp.float32)}
        emit = Emit.single(nxt, jnp.stack([token + 1, 0.0, 0.0, 0.0]),
                           out_degree, payload_width, when=inbox.count > 0)
        return new, emit
    return ring


def test_ring_token_passes():
    n = 64
    ring = ring_behavior()
    sys = BatchedSystem(capacity=n, behaviors=[ring], payload_width=4, out_degree=1)
    sys.spawn_block(ring, n)
    sys.tell(0, [1.0, 0, 0, 0])
    steps = 10
    for _ in range(steps):
        sys.step()
    received = sys.read_state("received")
    # token starts at actor 0 (step 1), then 1, ... one visit per step
    expected = np.zeros(n, dtype=np.int32)
    for k in range(steps):
        expected[k % n] += 1
    np.testing.assert_array_equal(received, expected)
    # token value increments as it travels
    last = sys.read_state("last")
    assert last[steps - 1] == float(steps)


def test_ring_wraps_and_scan_run():
    n = 8
    ring = ring_behavior()
    sys = BatchedSystem(capacity=n, behaviors=[ring], payload_width=4)
    sys.spawn_block(ring, n)
    sys.tell(0, [1.0, 0, 0, 0])
    sys.run(20)  # scan path
    received = sys.read_state("received")
    expected = np.zeros(n, dtype=np.int32)
    for k in range(20):
        expected[k % n] += 1
    np.testing.assert_array_equal(received, expected)


def test_fan_in_segment_sum():
    # 100 leaves each tell collector (id 0) value 1.0 every step; collector sums
    n_leaves = 100

    @behavior("leaf", {}, always_on=True)
    def leaf(state, inbox, ctx):
        return {}, Emit.single(0, jnp.array([1.0, 0, 0, 0]), 1, 4,
                               when=ctx.actor_id > 0)

    @behavior("collector", {"total": ((), jnp.float32), "msgs": ((), jnp.int32)})
    def collector(state, inbox, ctx):
        return {"total": state["total"] + inbox.sum[0],
                "msgs": state["msgs"] + inbox.count}, Emit.none(1, 4)

    sys = BatchedSystem(capacity=n_leaves + 1, behaviors=[collector, leaf],
                        payload_width=4)
    sys.spawn_block(collector, 1)
    sys.spawn_block(leaf, n_leaves)
    steps = 5
    for _ in range(steps):
        sys.step()
    # leaves emit on steps 1..5; deliveries land one step later
    assert sys.read_state("msgs")[0] == n_leaves * (steps - 1)
    assert sys.read_state("total")[0] == float(n_leaves * (steps - 1))


def test_ping_pong_pair():
    @behavior("pinger", {"hits": ((), jnp.int32)})
    def pinger(state, inbox, ctx):
        other = jnp.where(ctx.actor_id == 0, 1, 0)
        return ({"hits": state["hits"] + inbox.count},
                Emit.single(other, inbox.sum, 1, 4, when=inbox.count > 0))

    sys = BatchedSystem(capacity=2, behaviors=[pinger], payload_width=4)
    sys.spawn_block(pinger, 2)
    sys.tell(0, [1.0, 0, 0, 0])
    sys.run(10)
    hits = sys.read_state("hits")
    assert hits[0] + hits[1] == 10
    assert abs(int(hits[0]) - int(hits[1])) <= 1


def test_dead_actors_do_not_process():
    ring = ring_behavior()
    sys = BatchedSystem(capacity=4, behaviors=[ring], payload_width=4)
    ids = sys.spawn_block(ring, 4)
    sys.stop_block(ids[2:3])  # kill actor 2
    sys.tell(0, [1.0, 0, 0, 0])
    for _ in range(4):
        sys.step()
    received = sys.read_state("received")
    assert received[2] == 0  # dead actor never processed
    assert received[0] == 1 and received[1] == 1
    # token died at actor 2; actor 3 never got it
    assert received[3] == 0


def test_capacity_exhausted():
    ring = ring_behavior()
    sys = BatchedSystem(capacity=4, behaviors=[ring])
    sys.spawn_block(ring, 4)
    with pytest.raises(RuntimeError, match="capacity exhausted"):
        sys.spawn_block(ring, 1)


def test_heterogeneous_behaviors_switch():
    # two behavior types in one system: doubler forwards 2x to accumulator
    @behavior("doubler", {})
    def doubler(state, inbox, ctx):
        return {}, Emit.single(ctx.actor_id + 1, inbox.sum * 2.0, 1, 4,
                               when=inbox.count > 0)

    @behavior("acc", {"value": ((), jnp.float32)})
    def acc(state, inbox, ctx):
        return {"value": state["value"] + inbox.sum[0]}, Emit.none(1, 4)

    sys = BatchedSystem(capacity=2, behaviors=[doubler, acc], payload_width=4)
    sys.spawn_block(doubler, 1)
    sys.spawn_block(acc, 1)
    sys.tell(0, [21.0, 0, 0, 0])
    sys.step()  # doubler processes, emits 42 to actor 1
    sys.step()  # acc processes
    assert sys.read_state("value")[1] == 42.0


def test_out_of_range_dst_dropped():
    @behavior("spammer", {}, always_on=True)
    def spammer(state, inbox, ctx):
        return {}, Emit.single(999999, jnp.array([1.0, 0, 0, 0]), 1, 4)

    sys = BatchedSystem(capacity=2, behaviors=[spammer], payload_width=4)
    sys.spawn_block(spammer, 2)
    for _ in range(3):
        sys.step()  # must not crash; messages fall in drop bucket
    assert sys.pending_messages >= 0
