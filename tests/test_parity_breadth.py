"""Inventory gap-fills: typed Routers, stream BidiFlow + GraphDSL, and
ClusterClient (reference: typed/scaladsl/Routers.scala:24,36,
stream/scaladsl/BidiFlow.scala + GraphDSL.scala,
cluster-tools client/ClusterClient.scala:287)."""

import time

import pytest

from akka_tpu import ActorSystem as ClassicSystem
from akka_tpu.stream.dsl import BidiFlow, Flow, GraphDSL, Keep, Sink, Source
from akka_tpu.testkit import await_condition


@pytest.fixture()
def system():
    s = ClassicSystem("parity", {"akka": {"stdout-loglevel": "OFF"}})
    yield s
    s.terminate()
    s.await_termination(10)


# -- typed Routers ------------------------------------------------------------

def test_typed_pool_router(system):
    from akka_tpu.typed import Behaviors, Routers
    from akka_tpu.typed.adapter import props_from_behavior

    seen = []

    def worker():
        return Behaviors.receive_message(
            lambda msg: (seen.append(msg), Behaviors.same)[1])

    router = system.actor_of(
        props_from_behavior(Routers.pool(4, worker)), "pool-router")
    for i in range(12):
        router.tell(i)
    await_condition(lambda: len(seen) == 12, max_time=10.0)
    assert sorted(seen) == list(range(12))


def test_typed_pool_router_with_setup_behavior(system):
    """Regression (r3 review): Behaviors.setup results define __call__, so
    a bare callable() check would invoke them argument-less and crash —
    pool must accept Behavior INSTANCES including deferred ones."""
    from akka_tpu.typed import Behaviors, Routers
    from akka_tpu.typed.adapter import props_from_behavior

    seen = []

    def make(ctx):
        return Behaviors.receive_message(
            lambda msg: (seen.append(msg), Behaviors.same)[1])

    router = system.actor_of(
        props_from_behavior(Routers.pool(2, Behaviors.setup(make))),
        "setup-pool")
    for i in range(6):
        router.tell(i)
    await_condition(lambda: len(seen) == 6, max_time=10.0)


def test_typed_pool_router_dead_letters_when_all_routees_gone(system):
    from akka_tpu.actor.messages import DeadLetter
    from akka_tpu.typed import Behaviors, Routers
    from akka_tpu.typed.adapter import props_from_behavior

    dead = []
    system.event_stream.subscribe(dead.append, DeadLetter)

    def worker():
        return Behaviors.receive_message(lambda msg: Behaviors.stopped())

    router = system.actor_of(
        props_from_behavior(Routers.pool(2, worker)), "dying-pool")
    router.tell("kill-1")
    router.tell("kill-2")
    time.sleep(0.4)  # both children stop; Terminated prunes them
    router.tell("orphan")
    await_condition(
        lambda: any(getattr(d, "message", None) == "orphan" for d in dead),
        max_time=10.0, message="orphan message was silently dropped")


def test_typed_group_router(system):
    from akka_tpu.typed import Behaviors, Receptionist, Routers, ServiceKey
    from akka_tpu.typed.adapter import props_from_behavior

    key = ServiceKey("group-svc")
    seen = []

    def svc():
        return Behaviors.receive_message(
            lambda msg: (seen.append(msg), Behaviors.same)[1])

    workers = [system.actor_of(props_from_behavior(svc()), f"gsvc-{i}")
               for i in range(3)]
    recept = Receptionist.get(system)
    for w in workers:
        recept.register(key, w)
    router = system.actor_of(
        props_from_behavior(Routers.group(key)), "group-router")
    for i in range(9):
        router.tell(i)  # early messages buffer until the Listing arrives
    await_condition(lambda: len(seen) == 9, max_time=10.0)
    assert sorted(seen) == list(range(9))


# -- BidiFlow -----------------------------------------------------------------

def test_bidiflow_join_protocol_stack(system):
    # codec (int <-> str) atop framing (str <-> bytes) joined over an
    # echo transport: the classic protocol-stack shape
    codec = BidiFlow.from_functions(lambda i: str(i), lambda s: int(s) * 10)
    framing = BidiFlow.from_functions(lambda s: s.encode(),
                                      lambda b: b.decode())
    transport = Flow()  # loopback
    stack = codec.atop(framing).join(transport)
    out = Source.from_iterable([1, 2, 3]).via(stack) \
        .run_with(Sink.seq(), system).result(10.0)
    assert out == [10, 20, 30]


def test_bidiflow_reversed(system):
    bidi = BidiFlow.from_functions(lambda x: x + 1, lambda x: x * 2)
    rev = bidi.reversed()
    out = Source.from_iterable([1, 2]).via(rev.join(Flow())) \
        .run_with(Sink.seq(), system).result(10.0)
    assert out == [3, 5]  # *2 then +1


# -- GraphDSL -----------------------------------------------------------------

def test_graphdsl_diamond(system):
    def build(g):
        bcast = g.broadcast(2)
        merge = g.merge(2)
        g.edge(g.source(Source.from_iterable(range(10))),
               bcast.shape.in_)
        g.edge(g.flow(bcast.shape.outs[0], Flow().map(lambda x: x * 10)),
               merge.shape.ins[0])
        g.edge(g.flow(bcast.shape.outs[1], Flow().map(lambda x: x + 1000)),
               merge.shape.ins[1])
        return g.sink(Sink.seq(), merge.shape.out)

    out = GraphDSL.create(build).run(system).result(10.0)
    assert sorted(out) == sorted(
        [x * 10 for x in range(10)] + [x + 1000 for x in range(10)])


def test_graphdsl_zip_two_sources(system):
    def build(g):
        z = g.zip()
        g.edge(g.source(Source.from_iterable("abc")), z.shape.ins[0])
        g.edge(g.source(Source.from_iterable(range(3))), z.shape.ins[1])
        return g.sink(Sink.seq(), z.shape.out)

    out = GraphDSL.create(build).run(system).result(10.0)
    assert out == [("a", 0), ("b", 1), ("c", 2)]


# -- ClusterClient ------------------------------------------------------------

def test_cluster_client_roundtrip():
    from akka_tpu import Actor, Props, ask_sync
    from akka_tpu.cluster import Cluster
    from akka_tpu.cluster_tools import (ClusterClient,
                                        ClusterClientReceptionist,
                                        ClusterClientSettings)
    from akka_tpu.cluster_tools.client import Publish, Send, SendToAll
    from akka_tpu.remote.transport import InProcTransport

    InProcTransport.fault_injector.reset()
    cluster_sys = ClassicSystem.create("ccsrv", {
        "akka": {"actor": {"provider": "cluster"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}},
                 "cluster": {"gossip-interval": "0.05s",
                             "leader-actions-interval": "0.05s"}}})
    client_sys = ClassicSystem.create("ccext", {
        "akka": {"actor": {"provider": "remote"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}}}})
    try:
        Cluster.get(cluster_sys).join(
            str(cluster_sys.provider.local_address))

        class Service(Actor):
            def receive(self, message):
                self.sender.tell(("served", message), self.self_ref)

        svc = cluster_sys.actor_of(Props.create(Service), "the-service")
        recept = ClusterClientReceptionist.get(cluster_sys)
        recept.register_service(svc)

        contact = str(cluster_sys.provider.local_address)
        client = client_sys.actor_of(Props.create(
            ClusterClient,
            ClusterClientSettings(initial_contacts=(contact,))), "client")
        # messages sent BEFORE establishment buffer and then flow
        got = ask_sync(client, Send("/user/the-service", "hello"),
                       timeout=10.0, system=client_sys)
        assert got == ("served", "hello")
        got = ask_sync(client, SendToAll("/user/the-service", "all"),
                       timeout=10.0, system=client_sys)
        assert got == ("served", "all")
    finally:
        for s in (client_sys, cluster_sys):
            s.terminate()
        for s in (client_sys, cluster_sys):
            s.await_termination(10.0)
        InProcTransport.fault_injector.reset()
