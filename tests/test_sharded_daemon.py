"""ShardedDaemonProcess tests (VERDICT r4 missing #3) — modeled on the
reference's ShardedDaemonProcessSpec (akka-cluster-sharding-typed/src/test):
all N instances start without external messages, crashed instances are
revived by the keep-alive pinger, and instances stay singleton-per-index
while rehoming across node leave/join."""

import time

import pytest

from akka_tpu import ActorSystem
from akka_tpu.cluster import Cluster
from akka_tpu.remote.transport import InProcTransport
from akka_tpu.sharding import (ClusterShardingSettings, ClusterShardingTyped,
                               EntityTypeKey,
                               ShardedDaemonProcess,
                               ShardedDaemonProcessSettings)
from akka_tpu.testkit import TestProbe, await_condition
from akka_tpu.typed import Behaviors

FAST = {"akka": {"actor": {"provider": "cluster"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": 0}},
                 "cluster": {"gossip-interval": "0.05s",
                             "leader-actions-interval": "0.05s",
                             "unreachable-nodes-reaper-interval": "0.1s",
                             "failure-detector": {
                                 "heartbeat-interval": "0.1s",
                                 "acceptable-heartbeat-pause": "2s"}}}}


def _worker(system_name, starts):
    """Worker behavior factory: records (index, start-count), answers
    ("who", probe_ref) with (system, index), crashes on "boom"."""
    def factory(index):
        def setup(ctx):
            starts.append(index)

            def on_message(_ctx, msg):
                if isinstance(msg, tuple) and msg[0] == "who":
                    msg[1].tell((system_name, index))
                    return Behaviors.same()
                if msg == "boom":
                    raise RuntimeError(f"worker {index} crash")
                return Behaviors.same()
            return Behaviors.receive(on_message)
        return Behaviors.setup(setup)
    return factory


@pytest.fixture()
def one_node():
    InProcTransport.fault_injector.reset()
    s = ActorSystem.create("sdp0", FAST)
    c = Cluster.get(s)
    c.join(str(s.provider.local_address))
    await_condition(lambda: any(m.status.value == "Up"
                                for m in c.state.members), max_time=10.0)
    yield s
    s.terminate()
    s.await_termination(10.0)
    InProcTransport.fault_injector.reset()


def test_all_instances_start_without_messages(one_node):
    starts = []
    ShardedDaemonProcess.get(one_node).init(
        "ingest", 5, _worker("sdp0", starts),
        settings=ShardedDaemonProcessSettings(keep_alive_interval=0.3))
    await_condition(lambda: sorted(set(starts)) == [0, 1, 2, 3, 4],
                    max_time=10.0,
                    message=f"not all workers started: {sorted(set(starts))}")


def test_crashed_instance_is_revived_by_keep_alive(one_node):
    starts = []
    ShardedDaemonProcess.get(one_node).init(
        "revive", 3, _worker("sdp0", starts),
        settings=ShardedDaemonProcessSettings(keep_alive_interval=0.2))
    await_condition(lambda: sorted(set(starts)) == [0, 1, 2], max_time=10.0)
    sharding = ClusterShardingTyped.get(one_node)
    key = EntityTypeKey("sharded-daemon-process-revive")
    sharding.entity_ref_for(key, "1").tell("boom")
    # the next keep-alive ping must restart index 1 (a second start entry)
    await_condition(lambda: starts.count(1) >= 2, max_time=10.0,
                    message=f"worker 1 not revived: {starts}")
    probe = TestProbe(one_node)

    def alive_again():
        sharding.entity_ref_for(key, "1").tell(("who", probe.ref))
        try:
            return probe.receive_one(1.0) == ("sdp0", 1)
        except AssertionError:
            return False
    await_condition(alive_again, max_time=10.0)


def _region_entities(region, probe):
    from akka_tpu.testkit import region_entity_ids
    return region_entity_ids(region, probe)


def test_workers_rehome_across_leave_and_join():
    """Singleton-per-index through topology churn: workers spread over two
    nodes, collapse to the survivor when a node leaves, and spread again
    when a fresh node joins (reference: the keep-alive + one-shard-per-
    instance design, ShardedDaemonProcessImpl.scala)."""
    InProcTransport.fault_injector.reset()
    N = 4
    systems, starts = {}, {}

    def spawn(name):
        s = ActorSystem.create(name, FAST)
        systems[name] = s
        starts[name] = []
        return s

    s0 = spawn("sdpA")
    first = str(s0.provider.local_address)
    Cluster.get(s0).join(first)
    try:
        region0 = ShardedDaemonProcess.get(s0).init(
            "churn", N, _worker("sdpA", starts["sdpA"]),
            settings=ShardedDaemonProcessSettings(keep_alive_interval=0.2))
        probe0 = TestProbe(s0)
        await_condition(
            lambda: _region_entities(region0, probe0) ==
            {str(i) for i in range(N)}, max_time=15.0,
            message="workers did not all start on the single node")

        # second node joins and hosts the same daemon type
        s1 = spawn("sdpB")
        Cluster.get(s1).join(first)
        await_condition(lambda: all(
            len([m for m in Cluster.get(s).state.members
                 if m.status.value == "Up"]) == 2
            for s in (s0, s1)), max_time=15.0)
        region1 = ShardedDaemonProcess.get(s1).init(
            "churn", N, _worker("sdpB", starts["sdpB"]),
            settings=ShardedDaemonProcessSettings(keep_alive_interval=0.2))
        probe1 = TestProbe(s1)

        def spread_and_disjoint():
            e0 = _region_entities(region0, probe0)
            e1 = _region_entities(region1, probe1)
            if e0 is None or e1 is None:
                return False
            return (e0 | e1 == {str(i) for i in range(N)}
                    and not (e0 & e1) and e0 and e1)
        await_condition(spread_and_disjoint, max_time=20.0,
                        message="rebalance never spread the workers")

        # node B leaves: its workers must rehome to A (keep-alive revives
        # them there), each index still singleton
        s1.terminate()
        s1.await_termination(10.0)
        await_condition(
            lambda: _region_entities(region0, probe0) ==
            {str(i) for i in range(N)}, max_time=30.0,
            message="workers did not rehome to the survivor")

        # a fresh node joins ("rejoin"): workers spread once more
        s2 = spawn("sdpC")
        Cluster.get(s2).join(first)
        await_condition(lambda: all(
            len([m for m in Cluster.get(s).state.members
                 if m.status.value == "Up"]) == 2
            for s in (s0, s2)), max_time=20.0)
        region2 = ShardedDaemonProcess.get(s2).init(
            "churn", N, _worker("sdpC", starts["sdpC"]),
            settings=ShardedDaemonProcessSettings(keep_alive_interval=0.2))
        probe2 = TestProbe(s2)

        def spread_again():
            e0 = _region_entities(region0, probe0)
            e2 = _region_entities(region2, probe2)
            if e0 is None or e2 is None:
                return False
            return (e0 | e2 == {str(i) for i in range(N)}
                    and not (e0 & e2) and e0 and e2)
        await_condition(spread_again, max_time=30.0,
                        message="workers never spread to the rejoined node")
    finally:
        for s in systems.values():
            s.terminate()
        for s in systems.values():
            s.await_termination(10.0)
        InProcTransport.fault_injector.reset()
