"""Remoting tests: two actor systems in one process over the in-proc
transport (the multi-JVM specs' single-machine analogue, SURVEY.md §4.4)."""

import threading
import time

import pytest

from akka_tpu import Actor, ActorSystem, Props, Terminated, ask_sync
from akka_tpu.remote.provider import AddressTerminated, QuarantinedEvent
from akka_tpu.remote.transport import InProcTransport
from akka_tpu.serialization.serialization import Serialization
import numpy as np


def remote_system(name: str, port: int = 0) -> ActorSystem:
    return ActorSystem.create(name, {
        "akka": {"actor": {"provider": "remote"},
                 "stdout-loglevel": "OFF", "log-dead-letters": 0,
                 "remote": {"transport": "inproc",
                            "canonical": {"hostname": "local", "port": port}}}})


@pytest.fixture()
def two_systems():
    InProcTransport.fault_injector.reset()
    a = remote_system("sysA")
    b = remote_system("sysB")
    yield a, b
    for s in (a, b):
        s.terminate()
    for s in (a, b):
        assert s.await_termination(10.0)
    InProcTransport.fault_injector.reset()


class Echo(Actor):
    def receive(self, message):
        if message == "who":
            self.sender.tell(str(self.context.system.name), self.self_ref)
        else:
            self.sender.tell(("echo", message), self.self_ref)


def addr_of(system) -> str:
    a = system.provider.local_address
    return f"akka://{system.name}@{a.host}:{a.port}"


def test_large_message_lane_over_tcp():
    """VERDICT r2 missing #9: oversized payloads ride a DEDICATED lane
    (own TCP connection) so they can't head-of-line-block ordinary
    traffic — Artery's lane partitioning (ArteryTransport.scala:383-428)."""
    def tcp_system(name):
        return ActorSystem.create(name, {
            "akka": {"actor": {"provider": "remote"},
                     "stdout-loglevel": "OFF", "log-dead-letters": 0,
                     "remote": {"transport": "tcp",
                                "large-message-threshold": 4096,
                                "canonical": {"hostname": "127.0.0.1",
                                              "port": 0}}}})

    class BlobEcho(Actor):
        def receive(self, message):
            # no equality tests: ndarray == str is elementwise
            self.sender.tell(("echo", message), self.self_ref)

    a = tcp_system("laneA")
    b = tcp_system("laneB")
    try:
        b.actor_of(Props.create(BlobEcho), "echo")
        ref = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/echo")
        # ordinary-sized and oversized payloads both round-trip
        small = ask_sync(ref, "hi", timeout=10.0, system=a)
        assert small == ("echo", "hi")
        big = np.arange(1 << 16, dtype=np.float32)  # 256 KiB >> threshold
        got = ask_sync(ref, big, timeout=15.0, system=a)
        assert got[0] == "echo" and np.array_equal(got[1], big)
        # and they used SEPARATE per-lane connections
        lanes = {k[2] for k in a.provider.transport._conns}
        assert "large" in lanes, lanes
        assert lanes - {"large"}, f"no non-large lane used: {lanes}"
    finally:
        for s in (a, b):
            s.terminate()
        for s in (a, b):
            assert s.await_termination(10.0)


def test_remote_tell_and_reply(two_systems):
    a, b = two_systems
    b.actor_of(Props.create(Echo), "echo")
    time.sleep(0.1)
    remote_echo = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/echo")
    assert remote_echo is not a.dead_letters
    assert ask_sync(remote_echo, "who", timeout=5.0, system=a) == "sysB"
    assert ask_sync(remote_echo, ("x", 1), timeout=5.0, system=a) == ("echo", ("x", 1))


def test_remote_tensor_payload(two_systems):
    a, b = two_systems
    results = []
    got = threading.Event()

    class TensorSink(Actor):
        def receive(self, message):
            results.append(message)
            got.set()

    b.actor_of(Props.create(TensorSink), "sink")
    time.sleep(0.1)
    sink = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/sink")
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    sink.tell(arr)
    assert got.wait(5.0)
    np.testing.assert_array_equal(results[0], arr)


def test_remote_stop(two_systems):
    a, b = two_systems
    echo = b.actor_of(Props.create(Echo), "victim")
    time.sleep(0.1)
    remote = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/victim")
    remote.stop()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not echo.is_terminated:
        time.sleep(0.02)
    assert echo.is_terminated


def test_blackhole_drops_messages(two_systems):
    a, b = two_systems
    received = []

    class Sink(Actor):
        def receive(self, message):
            received.append(message)

    b.actor_of(Props.create(Sink), "sink")
    time.sleep(0.1)
    sink = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/sink")
    sink.tell("before")
    time.sleep(0.2)
    a_addr = f"{a.provider.local_address.host}:{a.provider.local_address.port}"
    b_addr = f"{b.provider.local_address.host}:{b.provider.local_address.port}"
    InProcTransport.fault_injector.blackhole(a_addr, b_addr)
    sink.tell("dropped")
    time.sleep(0.2)
    InProcTransport.fault_injector.pass_through(a_addr, b_addr)
    sink.tell("after")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and "after" not in received:
        time.sleep(0.02)
    assert received == ["before", "after"]


def test_quarantine_blocks_traffic(two_systems):
    a, b = two_systems
    b.actor_of(Props.create(Echo), "echo")
    time.sleep(0.1)
    remote = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/echo")
    assert ask_sync(remote, "who", timeout=5.0, system=a) == "sysB"
    events = []
    a.event_stream.subscribe(lambda e: events.append(e), QuarantinedEvent)
    assoc = a.provider._association(b.provider.local_address)
    a.provider.quarantine(b.provider.local_address, assoc.peer_uid)
    with pytest.raises(Exception):
        ask_sync(remote, "who", timeout=0.5, system=a)
    assert events and isinstance(events[0], QuarantinedEvent)


def test_serialization_round_trips():
    s = Serialization()
    for obj in ["hello", b"raw", {"k": [1, 2, 3]}, ("tuple", 1), 42,
                np.arange(6, dtype=np.int32).reshape(2, 3)]:
        out = s.verify_round_trip(obj)
        if isinstance(obj, np.ndarray):
            np.testing.assert_array_equal(out, obj)
        elif isinstance(obj, dict):
            assert out == obj
        else:
            assert out == obj or out == list(obj)  # json tuples -> lists


def test_serializer_binding_most_specific_wins():
    from akka_tpu.serialization.serialization import (JsonSerializer,
                                                      Serialization, Serializer)

    class MyMsg(dict):
        pass

    class MySerializer(Serializer):
        identifier = 99

        def to_binary(self, obj):
            return b"custom"

        def from_binary(self, data, manifest=""):
            return MyMsg(marker=True)

    s = Serialization()
    s.add_binding(MyMsg, MySerializer())
    sid, _, data = s.serialize(MyMsg(a=1))
    assert sid == 99 and data == b"custom"
    # plain dicts still use pickle fallback
    sid2, _, _ = s.serialize({"a": 1})
    assert sid2 != 99


def test_remote_watch_actor_level_graceful_stop(two_systems):
    """Watching a remote actor must produce Terminated when the actor stops
    normally while its node stays up (actor-level deathwatch, not just
    node-level; reference: RemoteWatcher + remote DeathWatchNotification)."""
    from akka_tpu import PoisonPill
    from akka_tpu.testkit import TestProbe

    a, b = two_systems
    target = b.actor_of(Props.create(Echo), "target")
    time.sleep(0.1)
    remote = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/target")
    probe = TestProbe(a)
    probe.watch(remote)
    time.sleep(0.2)  # let the Watch reach node b
    target.tell(PoisonPill)
    t = probe.expect_msg_class(Terminated, timeout=5.0)
    assert t.actor.path.elements == ("user", "target")


def test_remote_refs_inside_payloads(two_systems):
    """ActorRefs embedded in message payloads must survive the wire and be
    tell-able on the other side (reference: Serialization transport info)."""
    from akka_tpu.testkit import TestProbe

    a, b = two_systems

    class ReplyToInner(Actor):
        def receive(self, message):
            # message is ("reply-to", some_ref): answer that ref, not sender
            tag, ref = message
            ref.tell(("from", str(self.context.system.name)), self.self_ref)

    b.actor_of(Props.create(ReplyToInner), "inner")
    time.sleep(0.1)
    remote = a.provider.resolve_actor_ref(f"{addr_of(b)}/user/inner")
    probe = TestProbe(a)
    remote.tell(("reply-to", probe.ref))
    assert probe.receive_one(5.0) == ("from", "sysB")
